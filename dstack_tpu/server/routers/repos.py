"""/api/project/{project}/repos — parity: reference routers/repos.py
(init repo, upload code blob keyed by hash)."""

import hashlib
from typing import Optional

from pydantic import BaseModel

from dstack_tpu.errors import ResourceNotExistsError
from dstack_tpu.models.repos import AnyRunRepoData, RemoteRepoCreds
from dstack_tpu.server.http import Request, Router
from dstack_tpu.server.routers.deps import auth_project_member, get_ctx
from dstack_tpu.server.security import generate_id

router = Router()


class InitRepoRequest(BaseModel):
    repo_id: str
    repo_info: AnyRunRepoData
    # Clone URL + token/key for the runner-side git clone of remote repos;
    # stored encrypted at rest like secrets (parity: repo_creds table).
    repo_creds: Optional[RemoteRepoCreds] = None


class GetRepoRequest(BaseModel):
    repo_id: str


@router.post("/api/project/{project_name}/repos/init")
async def init_repo(request: Request, project_name: str):
    _, project_row = await auth_project_member(request, project_name)
    ctx = get_ctx(request)
    body = request.parse(InitRepoRequest)
    creds = (
        ctx.encryption.encrypt(body.repo_creds.model_dump_json())
        if body.repo_creds is not None
        else None
    )
    await ctx.db.execute(
        "INSERT INTO repos (id, project_id, name, type, info, creds)"
        " VALUES (?, ?, ?, ?, ?, ?)"
        " ON CONFLICT (project_id, name) DO UPDATE SET info = excluded.info,"
        " type = excluded.type,"
        " creds = COALESCE(excluded.creds, repos.creds)",
        (
            generate_id(),
            project_row["id"],
            body.repo_id,
            body.repo_info.repo_type,
            body.repo_info.model_dump_json(),
            creds,
        ),
    )
    return {}


@router.post("/api/project/{project_name}/repos/get")
async def get_repo(request: Request, project_name: str):
    _, project_row = await auth_project_member(request, project_name)
    body = request.parse(GetRepoRequest)
    row = await get_ctx(request).db.fetchone(
        "SELECT * FROM repos WHERE project_id = ? AND name = ?",
        (project_row["id"], body.repo_id),
    )
    if row is None:
        raise ResourceNotExistsError("Repo does not exist")
    import json

    return {"repo_id": row["name"], "repo_info": json.loads(row["info"])}


@router.post("/api/project/{project_name}/repos/upload_code")
async def upload_code(request: Request, project_name: str):
    """Raw blob body; repo_id passed as a query param. Returns the hash."""
    _, project_row = await auth_project_member(request, project_name)
    ctx = get_ctx(request)
    repo_id = request.query_param("repo_id")
    if not repo_id:
        raise ResourceNotExistsError("repo_id query param is required")
    repo_row = await ctx.db.fetchone(
        "SELECT * FROM repos WHERE project_id = ? AND name = ?",
        (project_row["id"], repo_id),
    )
    if repo_row is None:
        raise ResourceNotExistsError("Repo does not exist; call /repos/init first")
    blob = request.body
    blob_hash = hashlib.sha256(blob).hexdigest()
    # With object storage configured the DB row carries only the hash and
    # the bytes go to the bucket (parity: reference S3 offload,
    # services/storage.py); otherwise the blob lives in the codes table.
    stored_blob: Optional[bytes] = blob
    if ctx.blob_storage is not None:
        from dstack_tpu.server.services.storage import code_blob_key

        await ctx.blob_storage.put(code_blob_key(repo_row["id"], blob_hash), blob)
        stored_blob = None
    await ctx.db.execute(
        "INSERT INTO codes (id, repo_id, blob_hash, blob) VALUES (?, ?, ?, ?)"
        " ON CONFLICT (repo_id, blob_hash) DO NOTHING",
        (generate_id(), repo_row["id"], blob_hash, stored_blob),
    )
    return {"blob_hash": blob_hash}
