"""OpenAI-compatible model API: /proxy/models/{project}/...

Parity: src/dstack/_internal/proxy/lib/services/model_proxy/ — `/models`
listing plus chat-completions routed to the service replica that serves the
requested model, with format adapters:
  - openai: passthrough to the container's own OpenAI-compatible server
    (vLLM-TPU, JetStream+adapter)
  - tgi: translate chat-completions <-> TGI /generate

All upstream traffic rides the shared keep-alive pool (ctx.proxy_pool) and
the routing cache picks replicas (see services_proxy.py); SSE generations
stream chunk-by-chunk, non-stream completions buffer (their body is one
JSON object either way) but still reuse pooled connections.
"""

import json
import logging
import time
from typing import Any, Dict, List

import httpx

from dstack_tpu.dataplane.qos import DEFAULT_TENANT, TenantShedError
from dstack_tpu.errors import (
    BadRequestError,
    NoReplicasError,
    ResourceNotExistsError,
)
from dstack_tpu.server import settings
from dstack_tpu.server.http import Request, Response, Router
from dstack_tpu.server.routers.deps import get_ctx
from dstack_tpu.server.routers.services_proxy import pick_replica
from dstack_tpu.server.services.affinity import AffinityRequest
from dstack_tpu.utils.tracecontext import (
    REQUEST_ID_HEADER,
    TRACEPARENT_HEADER,
    child_traceparent,
    ensure_request_trace,
)

logger = logging.getLogger(__name__)

router = Router(prefix="/proxy/models")


def _tenant_of(request: Request, model_name: str) -> str:
    """Tenant identity for QoS + metrics: the API key when the caller
    sent one, else the adapter name (`base:adapter` model ids), else
    the shared default bucket. Matches the identity the engine's prefix
    cache namespaces KV blocks by."""
    auth = request.headers.get("authorization", "")
    if auth.lower().startswith("bearer "):
        token = auth[7:].strip()
        if token:
            return token
    if ":" in (model_name or ""):
        return model_name.split(":", 1)[1]
    return DEFAULT_TENANT


async def _service_models(ctx, project_name: str) -> List[Dict[str, Any]]:
    """All models served by RUNNING services of a project (cached; the
    routing cache invalidates on FSM job transitions + TTL)."""
    return await ctx.routing_cache.get_models(ctx, project_name)


@router.get("/{project_name}/models")
async def list_models(request: Request, project_name: str):
    models = await _service_models(get_ctx(request), project_name)
    return {
        "object": "list",
        "data": [
            {
                "id": m["name"],
                "object": "model",
                "created": 0,
                "owned_by": m["run_name"],
            }
            for m in models
        ],
    }


@router.post("/{project_name}/chat/completions")
async def chat_completions(request: Request, project_name: str):
    ctx = get_ctx(request)
    body = request.json() or {}
    model_name = body.get("model")
    if not model_name:
        raise BadRequestError("`model` is required")
    models = await _service_models(ctx, project_name)
    match = next((m for m in models if m["name"] == model_name), None)
    if match is None:
        raise ResourceNotExistsError(f"Model {model_name} not found")
    ctx.tracer.inc("proxy_requests", kind="model")
    tenant = _tenant_of(request, model_name)
    gate = getattr(ctx, "qos_gate", None)
    label = (
        gate.labels.label(tenant) if gate is not None else DEFAULT_TENANT
    )
    ctx.tracer.inc("serving_tenant_requests", tenant=label)
    if gate is not None:
        try:
            # Non-blocking rate check: a flooding tenant sheds HERE, at
            # the proxy, before its requests can queue in front of
            # other tenants' at the replica.
            gate.check(tenant)
        except TenantShedError as e:
            ctx.tracer.inc("serving_tenant_shed", tenant=label)
            ctx.service_stats.record_rejection(project_name, match["run_name"])
            recorder = getattr(ctx, "flight_recorder", None)
            if recorder is not None:
                # Shed requests are exactly the tail the capture exists
                # for. The dataplane middleware may already hold an open
                # trace for this request — close that one rather than
                # burning a second ring slot on the same id.
                rec = request.state.get("trace_rec")
                if rec is not None:
                    recorder.finish(rec, "shed")
                else:
                    tp, rid = ensure_request_trace(
                        request.state, request.headers
                    )
                    recorder.record_dropped(
                        rid, x_request_id=rid, traceparent=tp
                    )
            return Response(
                {"detail": str(e)},
                status=429,
                headers={"retry-after": str(max(1, int(e.retry_after + 0.5)))},
            )
    t0 = time.monotonic()
    # Cache-affinity selection: the router hashes the request's prompt
    # into the engine's prefix chain keys and prefers a replica whose
    # gossiped sketch shows those blocks resident. `base:adapter` model
    # ids additionally steer toward adapter-resident replicas so a pick
    # never forces an adapter swap another replica could avoid.
    affinity = AffinityRequest(
        messages=body.get("messages", ()) or (),
        adapter=match.get("adapter"),
    )
    try:
        target = await pick_replica(
            ctx, project_name, match["run_name"], affinity=affinity
        )
    except NoReplicasError:
        # Demand against a service with no live replica still counts as
        # RPS — it is exactly the scale-from-zero wake signal. The
        # routing cache never caches this answer, so the next request
        # re-checks; meanwhile the caller gets a retryable 503 with a
        # Retry-After sized from the service's last OBSERVED cold-start
        # budget (stats.py), not a bare client error — "warming up" is
        # the server's condition, not the caller's mistake.
        ctx.service_stats.record(project_name, match["run_name"])
        ctx.service_stats.note_no_replicas(project_name, match["run_name"])
        retry_after = ctx.service_stats.get_retry_after(
            project_name, match["run_name"]
        )
        return Response(
            {"detail": f"Service {match['run_name']} has no running"
                       " replicas yet (scaling from zero); retry after"
                       f" {int(retry_after + 0.5)}s"},
            status=503,
            headers={"retry-after": str(max(1, int(retry_after + 0.5)))},
        )
    except Exception:
        ctx.service_stats.record(project_name, match["run_name"])
        raise
    ctx.service_stats.note_replicas_available(project_name, match["run_name"])
    if match["format"] == "tgi":
        resp = await _tgi_chat(ctx, request, target, target.base_url, body)
    else:
        resp = await _openai_passthrough(
            ctx, request, target, target.base_url + match["prefix"], body
        )
    if resp.status in (429, 503):
        # Replica shed the request (serving-engine admission control).
        # Count it ONLY as a rejection — the autoscaler folds shed
        # demand back into RPS itself; counting it in both streams
        # would double the scale-up pressure.
        ctx.tracer.inc("serving_tenant_shed", tenant=label)
        ctx.service_stats.record_rejection(project_name, match["run_name"])
    else:
        elapsed = time.monotonic() - t0
        ctx.service_stats.record(project_name, match["run_name"])
        # TTFT approximation at the proxy: request -> upstream headers
        # (streams return the moment TTFB lands, buffered bodies add
        # generation time — both are what the user waited). Feeds the
        # SLO autoscaler's windowed p95 and the per-tenant histogram.
        ctx.service_stats.observe_latency(
            project_name, match["run_name"], elapsed, metric="ttft"
        )
        ctx.tracer.observe("serving_tenant_ttft_seconds", elapsed, tenant=label)
    return resp


def _fwd_headers(request: Request) -> Dict[str, str]:
    """Trace propagation headers for an upstream call: a child of the
    request's traceparent (same trace_id, this hop's span_id) plus the
    client-correlatable X-Request-ID — so replica-side spans and the
    engine flight recorder join the trace that entered the proxy."""
    tp, rid = ensure_request_trace(request.state, request.headers)
    return {TRACEPARENT_HEADER: child_traceparent(tp), REQUEST_ID_HEADER: rid}


def _proxy_headers(upstream) -> Dict[str, str]:
    """Headers an upstream error/response must keep through the proxy:
    content-type, and the Retry-After backpressure hint on sheds."""
    headers = {"content-type": upstream.headers.get("content-type", "application/json")}
    if "retry-after" in upstream.headers:
        headers["retry-after"] = upstream.headers["retry-after"]
    return headers


def _upstream_error(ctx, target, e: Exception) -> Response:
    ctx.tracer.inc("proxy_upstream_errors", kind="model")
    if isinstance(e, (httpx.ConnectError, httpx.ConnectTimeout)):
        # Trip the breaker so the next pick skips this replica for the
        # cooldown (POSTs are not replayed — generation is not idempotent).
        ctx.routing_cache.mark_failure(target.job_id)
    return Response({"detail": f"Model backend unreachable: {e}"}, status=502)


async def _openai_passthrough(
    ctx, request: Request, target, base: str, body: Dict[str, Any]
) -> Response:
    if body.get("stream"):
        return await _openai_stream(ctx, request, target, base, body)
    client = ctx.proxy_pool.acquire(base)
    ctx.routing_cache.start(target.job_id)
    start = time.monotonic()
    try:
        upstream = await client.post(
            f"{base}/chat/completions", json=body,
            headers=_fwd_headers(request),
            timeout=settings.PROXY_MODEL_TIMEOUT,
        )
    except httpx.HTTPError as e:
        return _upstream_error(ctx, target, e)
    finally:
        ctx.routing_cache.finish(target.job_id)
        ctx.proxy_pool.release(base)
    ctx.proxy_pool.observe_ttfb("model", time.monotonic() - start)
    ctx.routing_cache.mark_success(target.job_id)
    return Response(
        upstream.content,
        status=upstream.status_code,
        headers=_proxy_headers(upstream),
    )


async def _openai_stream(
    ctx, request: Request, target, base: str, body: Dict[str, Any]
) -> Response:
    """Token-by-token SSE relay: forward upstream chunks as they arrive
    instead of buffering the full generation (reference model proxy streams).
    Upstream errors keep their status/body rather than masquerading as a
    successful empty stream."""
    client = ctx.proxy_pool.acquire(base)
    ctx.routing_cache.start(target.job_id)
    start = time.monotonic()
    try:
        upstream = await client.send(
            client.build_request(
                "POST",
                f"{base}/chat/completions",
                json=body,
                headers=_fwd_headers(request),
                timeout=settings.PROXY_MODEL_TIMEOUT,
            ),
            stream=True,
        )
    except httpx.HTTPError as e:
        ctx.routing_cache.finish(target.job_id)
        ctx.proxy_pool.release(base)
        return _upstream_error(ctx, target, e)
    ctx.proxy_pool.observe_ttfb("model", time.monotonic() - start)
    ctx.routing_cache.mark_success(target.job_id)
    if upstream.status_code != 200:
        content = await upstream.aread()
        await upstream.aclose()
        ctx.routing_cache.finish(target.job_id)
        ctx.proxy_pool.release(base)
        return Response(
            content,
            status=upstream.status_code,
            headers=_proxy_headers(upstream),
        )

    async def _gen():
        # The pooled client stays leased until the last chunk: release
        # happens here, never in the handler, so pool eviction cannot
        # close a client under an in-flight generation.
        try:
            async for chunk in upstream.aiter_bytes():
                yield chunk
        except httpx.HTTPError:
            pass  # mid-stream disconnect: terminate the chunked response
        finally:
            await upstream.aclose()
            ctx.routing_cache.finish(target.job_id)
            ctx.proxy_pool.release(base)

    return Response(
        stream=_gen(),
        media_type=upstream.headers.get("content-type", "text/event-stream"),
    )


def _messages_to_prompt(messages: List[Dict[str, Any]]) -> str:
    """Minimal chat template for TGI backends without one (reference:
    model_proxy/clients/tgi.py renders the model's chat_template; without
    tokenizer access we use a plain role-tagged prompt)."""
    parts = []
    for m in messages:
        parts.append(f"<|{m.get('role', 'user')}|>\n{m.get('content', '')}")
    parts.append("<|assistant|>\n")
    return "\n".join(parts)


async def _tgi_chat(
    ctx, request: Request, target, base: str, body: Dict[str, Any]
) -> Response:
    if body.get("stream"):
        # TGI translation is request/response; a buffered body dressed up as
        # a chat.completion would break SSE-iterating SDKs, so be explicit.
        raise BadRequestError("stream=true is not supported for tgi-format models")
    prompt = _messages_to_prompt(body.get("messages", []))
    parameters: Dict[str, Any] = {
        "max_new_tokens": body.get("max_tokens", 512),
        "stop": body.get("stop") or [],
    }
    # `is not None`, not truthiness: temperature=0 / top_p=0 are valid
    # greedy-decoding settings and must pass through.
    if body.get("temperature") is not None:
        parameters["temperature"] = body["temperature"]
    if body.get("top_p") is not None:
        parameters["top_p"] = body["top_p"]
    tgi_body = {"inputs": prompt, "parameters": parameters}
    client = ctx.proxy_pool.acquire(base)
    ctx.routing_cache.start(target.job_id)
    start = time.monotonic()
    try:
        upstream = await client.post(
            f"{base}/generate", json=tgi_body,
            headers=_fwd_headers(request),
            timeout=settings.PROXY_MODEL_TIMEOUT,
        )
    except httpx.HTTPError as e:
        return _upstream_error(ctx, target, e)
    finally:
        ctx.routing_cache.finish(target.job_id)
        ctx.proxy_pool.release(base)
    ctx.proxy_pool.observe_ttfb("model", time.monotonic() - start)
    ctx.routing_cache.mark_success(target.job_id)
    if upstream.status_code != 200:
        return Response(
            upstream.content, status=upstream.status_code,
            headers=_proxy_headers(upstream),
        )
    generated = upstream.json().get("generated_text", "")
    return Response(
        {
            "id": f"chatcmpl-{int(time.time() * 1000)}",
            "object": "chat.completion",
            "created": int(time.time()),
            "model": body.get("model"),
            "choices": [
                {
                    "index": 0,
                    "message": {"role": "assistant", "content": generated},
                    "finish_reason": "stop",
                }
            ],
            "usage": {},
        }
    )
