"""OpenAI-compatible model API: /proxy/models/{project}/...

Parity: src/dstack/_internal/proxy/lib/services/model_proxy/ — `/models`
listing plus chat-completions routed to the service replica that serves the
requested model, with format adapters:
  - openai: passthrough to the container's own OpenAI-compatible server
    (vLLM-TPU, JetStream+adapter)
  - tgi: translate chat-completions <-> TGI /generate
"""

import json
import logging
import time
from typing import Any, Dict, List, Optional, Tuple

import httpx

from dstack_tpu.errors import BadRequestError, ResourceNotExistsError
from dstack_tpu.server.http import Request, Response, Router
from dstack_tpu.server.routers.deps import get_ctx

logger = logging.getLogger(__name__)

router = Router(prefix="/proxy/models")


async def _service_models(ctx, project_name: str) -> List[Dict[str, Any]]:
    """All models served by RUNNING services of a project."""
    project_row = await ctx.db.fetchone(
        "SELECT * FROM projects WHERE name = ? AND deleted = 0", (project_name,)
    )
    if project_row is None:
        raise ResourceNotExistsError("Project not found")
    rows = await ctx.db.fetchall(
        "SELECT * FROM runs WHERE project_id = ? AND deleted = 0"
        " AND service_spec IS NOT NULL AND status = 'running'",
        (project_row["id"],),
    )
    models = []
    for row in rows:
        spec = json.loads(row["service_spec"])
        model = spec.get("model")
        if model:
            models.append(
                {
                    "run_id": row["id"],
                    "run_name": row["run_name"],
                    "name": model["name"],
                    "format": model.get("format", "openai"),
                    "prefix": model.get("prefix", "/v1"),
                }
            )
    return models


@router.get("/{project_name}/models")
async def list_models(request: Request, project_name: str):
    models = await _service_models(get_ctx(request), project_name)
    return {
        "object": "list",
        "data": [
            {
                "id": m["name"],
                "object": "model",
                "created": 0,
                "owned_by": m["run_name"],
            }
            for m in models
        ],
    }


@router.post("/{project_name}/chat/completions")
async def chat_completions(request: Request, project_name: str):
    ctx = get_ctx(request)
    body = request.json() or {}
    model_name = body.get("model")
    if not model_name:
        raise BadRequestError("`model` is required")
    models = await _service_models(ctx, project_name)
    match = next((m for m in models if m["name"] == model_name), None)
    if match is None:
        raise ResourceNotExistsError(f"Model {model_name} not found")
    from dstack_tpu.server.routers.services_proxy import pick_replica

    try:
        jpd, port = await pick_replica(ctx, project_name, match["run_name"])
    except Exception:
        # Demand against a service with no live replica still counts as
        # RPS — it is exactly the scale-from-zero wake signal.
        ctx.service_stats.record(project_name, match["run_name"])
        raise
    base = f"http://{jpd.hostname}:{port}"
    if match["format"] == "tgi":
        resp = await _tgi_chat(base, body)
    else:
        resp = await _openai_passthrough(base + match["prefix"], body)
    if resp.status in (429, 503):
        # Replica shed the request (serving-engine admission control).
        # Count it ONLY as a rejection — the autoscaler folds shed
        # demand back into RPS itself; counting it in both streams
        # would double the scale-up pressure.
        ctx.service_stats.record_rejection(project_name, match["run_name"])
    else:
        ctx.service_stats.record(project_name, match["run_name"])
    return resp


def _proxy_headers(upstream) -> Dict[str, str]:
    """Headers an upstream error/response must keep through the proxy:
    content-type, and the Retry-After backpressure hint on sheds."""
    headers = {"content-type": upstream.headers.get("content-type", "application/json")}
    if "retry-after" in upstream.headers:
        headers["retry-after"] = upstream.headers["retry-after"]
    return headers


async def _openai_passthrough(base: str, body: Dict[str, Any]) -> Response:
    if body.get("stream"):
        return await _openai_stream(base, body)
    try:
        async with httpx.AsyncClient(timeout=300.0) as client:
            upstream = await client.post(f"{base}/chat/completions", json=body)
    except httpx.HTTPError as e:
        return Response({"detail": f"Model backend unreachable: {e}"}, status=502)
    return Response(
        upstream.content,
        status=upstream.status_code,
        headers=_proxy_headers(upstream),
    )


async def _openai_stream(base: str, body: Dict[str, Any]) -> Response:
    """Token-by-token SSE relay: forward upstream chunks as they arrive
    instead of buffering the full generation (reference model proxy streams).
    Upstream errors keep their status/body rather than masquerading as a
    successful empty stream."""
    client = httpx.AsyncClient(timeout=300.0)
    try:
        upstream = await client.send(
            client.build_request("POST", f"{base}/chat/completions", json=body),
            stream=True,
        )
    except httpx.HTTPError as e:
        await client.aclose()
        return Response({"detail": f"Model backend unreachable: {e}"}, status=502)
    if upstream.status_code != 200:
        content = await upstream.aread()
        await upstream.aclose()
        await client.aclose()
        return Response(
            content,
            status=upstream.status_code,
            headers=_proxy_headers(upstream),
        )

    async def _gen():
        try:
            async for chunk in upstream.aiter_bytes():
                yield chunk
        except httpx.HTTPError:
            pass  # mid-stream disconnect: terminate the chunked response
        finally:
            await upstream.aclose()
            await client.aclose()

    return Response(
        stream=_gen(),
        media_type=upstream.headers.get("content-type", "text/event-stream"),
    )


def _messages_to_prompt(messages: List[Dict[str, Any]]) -> str:
    """Minimal chat template for TGI backends without one (reference:
    model_proxy/clients/tgi.py renders the model's chat_template; without
    tokenizer access we use a plain role-tagged prompt)."""
    parts = []
    for m in messages:
        parts.append(f"<|{m.get('role', 'user')}|>\n{m.get('content', '')}")
    parts.append("<|assistant|>\n")
    return "\n".join(parts)


async def _tgi_chat(base: str, body: Dict[str, Any]) -> Response:
    if body.get("stream"):
        # TGI translation is request/response; a buffered body dressed up as
        # a chat.completion would break SSE-iterating SDKs, so be explicit.
        raise BadRequestError("stream=true is not supported for tgi-format models")
    prompt = _messages_to_prompt(body.get("messages", []))
    tgi_body = {
        "inputs": prompt,
        "parameters": {
            "max_new_tokens": body.get("max_tokens", 512),
            "temperature": body.get("temperature") or None,
            "top_p": body.get("top_p") or None,
            "stop": body.get("stop") or [],
        },
    }
    try:
        async with httpx.AsyncClient(timeout=300.0) as client:
            upstream = await client.post(f"{base}/generate", json=tgi_body)
    except httpx.HTTPError as e:
        return Response({"detail": f"Model backend unreachable: {e}"}, status=502)
    if upstream.status_code != 200:
        return Response(
            upstream.content, status=upstream.status_code,
            headers=_proxy_headers(upstream),
        )
    generated = upstream.json().get("generated_text", "")
    return Response(
        {
            "id": f"chatcmpl-{int(time.time() * 1000)}",
            "object": "chat.completion",
            "created": int(time.time()),
            "model": body.get("model"),
            "choices": [
                {
                    "index": 0,
                    "message": {"role": "assistant", "content": generated},
                    "finish_reason": "stop",
                }
            ],
            "usage": {},
        }
    )
