"""/api/project/{project}/gateways — parity: reference routers/gateways.py."""

from typing import List, Optional

from pydantic import BaseModel

from dstack_tpu.errors import ResourceExistsError, ResourceNotExistsError
from dstack_tpu.models.gateways import Gateway, GatewayConfiguration, GatewayStatus
from dstack_tpu.server.http import Request, Router
from dstack_tpu.server.routers.deps import auth_project_member, get_ctx
from dstack_tpu.server.security import generate_id
from dstack_tpu.server.services.shard_map import shard_of
from dstack_tpu.utils.common import parse_dt, utcnow_iso

router = Router()


class CreateGatewayRequest(BaseModel):
    configuration: GatewayConfiguration


class GatewayNameRequest(BaseModel):
    name: str


class DeleteGatewaysRequest(BaseModel):
    names: List[str]


async def _row_to_gateway(ctx, row) -> Gateway:
    ip = None
    hostname = None
    if row["gateway_compute_id"]:
        compute_row = await ctx.db.fetchone(
            "SELECT * FROM gateway_computes WHERE id = ?", (row["gateway_compute_id"],)
        )
        if compute_row is not None:
            ip = compute_row["ip_address"]
            hostname = compute_row["hostname"]
    return Gateway(
        id=row["id"],
        name=row["name"],
        project_name="",
        configuration=GatewayConfiguration.model_validate_json(row["configuration"]),
        created_at=parse_dt(row["created_at"]),
        status=GatewayStatus(row["status"]),
        status_message=row["status_message"],
        ip_address=ip,
        hostname=hostname,
        default=bool(row["is_default"]),
    )


@router.post("/api/project/{project_name}/gateways/create")
async def create_gateway(request: Request, project_name: str):
    _, project_row = await auth_project_member(request, project_name)
    ctx = get_ctx(request)
    body = request.parse(CreateGatewayRequest)
    name = body.configuration.name or f"gateway-{generate_id()[:8]}"
    body.configuration.name = name
    existing = await ctx.db.fetchone(
        "SELECT id FROM gateways WHERE project_id = ? AND name = ?",
        (project_row["id"], name),
    )
    if existing is not None:
        raise ResourceExistsError(f"Gateway {name} already exists")
    now = utcnow_iso()
    gateway_id = generate_id()
    await ctx.db.execute(
        "INSERT INTO gateways (id, project_id, name, status, configuration,"
        " created_at, last_processed_at, is_default, shard)"
        " VALUES (?, ?, ?, ?, ?, ?, ?, ?, ?)",
        (
            gateway_id, project_row["id"], name, GatewayStatus.SUBMITTED.value,
            body.configuration.model_dump_json(), now, now,
            1 if body.configuration.default else 0, shard_of(gateway_id),
        ),
    )
    ctx.kick("gateways")
    row = await ctx.db.fetchone(
        "SELECT * FROM gateways WHERE project_id = ? AND name = ?",
        (project_row["id"], name),
    )
    return await _row_to_gateway(ctx, row)


@router.post("/api/project/{project_name}/gateways/list")
async def list_gateways(request: Request, project_name: str):
    _, project_row = await auth_project_member(request, project_name)
    ctx = get_ctx(request)
    rows = await ctx.db.fetchall(
        "SELECT * FROM gateways WHERE project_id = ? ORDER BY name", (project_row["id"],)
    )
    return [(await _row_to_gateway(ctx, r)).model_dump() for r in rows]


@router.post("/api/project/{project_name}/gateways/get")
async def get_gateway(request: Request, project_name: str):
    _, project_row = await auth_project_member(request, project_name)
    ctx = get_ctx(request)
    body = request.parse(GatewayNameRequest)
    row = await ctx.db.fetchone(
        "SELECT * FROM gateways WHERE project_id = ? AND name = ?",
        (project_row["id"], body.name),
    )
    if row is None:
        raise ResourceNotExistsError(f"Gateway {body.name} does not exist")
    return await _row_to_gateway(ctx, row)


@router.post("/api/project/{project_name}/gateways/delete")
async def delete_gateways(request: Request, project_name: str):
    _, project_row = await auth_project_member(request, project_name)
    ctx = get_ctx(request)
    body = request.parse(DeleteGatewaysRequest)
    for name in body.names:
        row = await ctx.db.fetchone(
            "SELECT * FROM gateways WHERE project_id = ? AND name = ?",
            (project_row["id"], name),
        )
        if row is None:
            continue
        if row["gateway_compute_id"]:
            compute_row = await ctx.db.fetchone(
                "SELECT * FROM gateway_computes WHERE id = ?", (row["gateway_compute_id"],)
            )
            if compute_row is not None and compute_row["provisioning_data"]:
                from dstack_tpu.models.gateways import GatewayProvisioningData
                from dstack_tpu.server.services import backends as backends_service

                pd = GatewayProvisioningData.model_validate_json(
                    compute_row["provisioning_data"]
                )
                conf = GatewayConfiguration.model_validate_json(row["configuration"])
                try:
                    compute = await backends_service.get_project_backend(
                        ctx, project_row["id"], conf.backend
                    )
                    await compute.terminate_gateway(pd.instance_id, pd.region)
                except Exception:
                    pass
        await ctx.db.execute("DELETE FROM gateways WHERE id = ?", (row["id"],))
    return {}
