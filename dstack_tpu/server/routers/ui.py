"""Web console: serves the static single-page app from dstack_tpu/ui/.

Parity: reference frontend/ (React 18, 15.6k LoC TS, built by node and
served by the FastAPI app from a wheel-bundled dist). Redesign: a
dependency-free vanilla-JS SPA shipped inside the Python package — no node
toolchain, no build step, same dashboards (runs/fleets/instances/volumes/
gateways/backends + live logs) against the same JSON API.
"""

from pathlib import Path

from dstack_tpu.server.http import Request, Response, Router

router = Router()

UI_DIR = Path(__file__).resolve().parent.parent.parent / "ui"

# Whitelist instead of path arithmetic: no traversal surface.
_ASSETS = {
    "index.html": "text/html; charset=utf-8",
    "app.js": "application/javascript; charset=utf-8",
    "style.css": "text/css; charset=utf-8",
}


def _serve(name: str) -> Response:
    media_type = _ASSETS.get(name)
    if media_type is None:
        return Response({"detail": "Not found"}, status=404)
    path = UI_DIR / name
    if not path.exists():
        return Response({"detail": "Not found"}, status=404)
    return Response(path.read_bytes(), media_type=media_type)


@router.get("/")
async def index(request: Request) -> Response:
    return Response(
        None, status=307, headers={"location": "/ui/"}
    )


@router.get("/ui/")
async def ui_index(request: Request) -> Response:
    return _serve("index.html")


@router.get("/ui/{asset}")
async def ui_asset(request: Request, asset: str) -> Response:
    return _serve(asset)
