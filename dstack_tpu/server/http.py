"""Minimal asyncio HTTP/1.1 framework: router, request/response, server.

The reference runs FastAPI+uvicorn (server/app.py:67-188); neither is in this
environment, so the control plane ships its own small framework. It covers
exactly what the API surface needs: path params, JSON bodies validated by
pydantic, bearer auth hooks, typed ApiError → JSON mapping, keep-alive,
streaming responses (log follow), and WebSocket upgrades (attach/logs_ws).
"""

import asyncio
import base64
import hashlib
import json
import logging
import re
import struct
import traceback
from dataclasses import dataclass, field
from typing import (
    Any,
    AsyncIterator,
    Awaitable,
    Callable,
    Dict,
    List,
    Optional,
    Tuple,
    Union,
)
from urllib.parse import parse_qs, unquote

from pydantic import BaseModel, ValidationError

from dstack_tpu.errors import ApiError, ConfigurationError

logger = logging.getLogger(__name__)

MAX_BODY = 512 * 1024 * 1024  # code uploads can be large
MAX_HEADER = 64 * 1024


class Request:
    def __init__(
        self,
        method: str,
        path: str,
        query: Dict[str, List[str]],
        headers: Dict[str, str],
        body: bytes,
        path_params: Optional[Dict[str, str]] = None,
    ):
        self.method = method
        self.path = path
        self.query = query
        self.headers = headers
        self.body = body
        self.path_params: Dict[str, str] = path_params or {}
        self.state: Dict[str, Any] = {}  # per-request context (auth user, ...)

    def json(self) -> Any:
        if not self.body:
            return None
        try:
            return json.loads(self.body)
        except json.JSONDecodeError as e:
            raise ApiError(f"Invalid JSON body: {e}") from e

    def parse(self, model: type) -> Any:
        """Validate the JSON body against a pydantic model."""
        data = self.json()
        if data is None:
            data = {}
        try:
            return model.model_validate(data)
        except ValidationError as e:
            raise ApiError(
                "Request validation error",
                details=[
                    {
                        "msg": err.get("msg"),
                        "loc": list(err.get("loc", ())),
                        "code": "validation_error",
                    }
                    for err in e.errors()
                ],
            ) from e

    def query_param(self, name: str, default: Optional[str] = None) -> Optional[str]:
        vals = self.query.get(name)
        return vals[0] if vals else default

    @property
    def bearer_token(self) -> Optional[str]:
        auth = self.headers.get("authorization", "")
        if auth.lower().startswith("bearer "):
            return auth[7:].strip()
        return None


class Response:
    def __init__(
        self,
        content: Union[bytes, str, dict, list, BaseModel, None] = None,
        status: int = 200,
        headers: Optional[Dict[str, str]] = None,
        media_type: Optional[str] = None,
        stream: Optional[AsyncIterator[bytes]] = None,
    ):
        self.status = status
        self.headers = headers or {}
        self.stream = stream
        if stream is not None:
            self.body = b""
            self.headers.setdefault("content-type", media_type or "application/octet-stream")
        elif isinstance(content, BaseModel):
            self.body = content.model_dump_json().encode()
            self.headers.setdefault("content-type", "application/json")
        elif isinstance(content, (dict, list)):
            self.body = json.dumps(content, default=_json_default).encode()
            self.headers.setdefault("content-type", "application/json")
        elif isinstance(content, str):
            self.body = content.encode()
            self.headers.setdefault("content-type", media_type or "text/plain; charset=utf-8")
        elif content is None:
            self.body = b""
        else:
            self.body = content
            self.headers.setdefault("content-type", media_type or "application/octet-stream")


def _json_default(o: Any) -> Any:
    import datetime
    import enum
    import uuid

    if isinstance(o, BaseModel):
        return json.loads(o.model_dump_json())
    if isinstance(o, (datetime.datetime, datetime.date)):
        return o.isoformat()
    if isinstance(o, enum.Enum):
        return o.value
    if isinstance(o, uuid.UUID):
        return str(o)
    raise TypeError(f"Cannot serialize {type(o)}")


Handler = Callable[..., Awaitable[Union[Response, BaseModel, dict, list, str, None]]]

_PARAM_RE = re.compile(r"\{([a-zA-Z_][a-zA-Z0-9_]*)\}")


def _compile_path(pattern: str) -> re.Pattern:
    regex = _PARAM_RE.sub(lambda m: f"(?P<{m.group(1)}>[^/]+)", pattern.rstrip("/") or "/")
    return re.compile(f"^{regex}/?$")


@dataclass
class Route:
    method: str
    pattern: str
    regex: re.Pattern
    handler: Handler
    websocket: bool = False
    # OpenAPI metadata (openapi.py); request model may also be inferred
    # from the handler body's `request.parse(Model)` call.
    request_model: Optional[type] = None
    response_model: Optional[type] = None


class Router:
    """A group of routes under a common prefix."""

    def __init__(self, prefix: str = ""):
        self.prefix = prefix.rstrip("/")
        self.routes: List[Route] = []

    def add(self, method: str, path: str, handler: Handler, websocket: bool = False,
            request_model: Optional[type] = None,
            response_model: Optional[type] = None) -> None:
        full = self.prefix + path
        self.routes.append(Route(method.upper(), full, _compile_path(full), handler,
                                 websocket, request_model, response_model))

    def post(self, path: str, **meta) -> Callable[[Handler], Handler]:
        return self._decorator("POST", path, **meta)

    def get(self, path: str, **meta) -> Callable[[Handler], Handler]:
        return self._decorator("GET", path, **meta)

    def delete(self, path: str, **meta) -> Callable[[Handler], Handler]:
        return self._decorator("DELETE", path, **meta)

    def websocket(self, path: str) -> Callable[[Handler], Handler]:
        def deco(fn: Handler) -> Handler:
            self.add("GET", path, fn, websocket=True)
            return fn

        return deco

    def _decorator(self, method: str, path: str, **meta) -> Callable[[Handler], Handler]:
        def deco(fn: Handler) -> Handler:
            self.add(method, path, fn, **meta)
            return fn

        return deco


Middleware = Callable[[Request], Awaitable[Optional[Response]]]
ResponseHook = Callable[[Request, Response], None]


class App:
    """Route table + middleware + lifespan, served by `Server`."""

    def __init__(self):
        self.routers: List[Router] = []
        self.middleware: List[Middleware] = []
        # Middleware is PRE-only (short-circuit or pass); response hooks are
        # the POST side — synchronous header stampers (request-id echo,
        # traceparent) that run on every response, including middleware
        # short-circuits and error responses.
        self.response_hooks: List[ResponseHook] = []
        self.on_startup: List[Callable[[], Awaitable[None]]] = []
        self.on_shutdown: List[Callable[[], Awaitable[None]]] = []
        self.state: Dict[str, Any] = {}

    def include_router(self, router: Router) -> None:
        self.routers.append(router)

    def add_middleware(self, mw: Middleware) -> None:
        self.middleware.append(mw)

    def add_response_hook(self, hook: ResponseHook) -> None:
        self.response_hooks.append(hook)

    def _apply_response_hooks(self, request: Request, resp: Response) -> Response:
        for hook in self.response_hooks:
            try:
                hook(request, resp)
            except Exception:
                logger.exception("response hook failed")
        return resp

    def _find_route(self, method: str, path: str) -> Tuple[Optional[Route], Dict[str, str], bool]:
        path_matched = False
        for router in self.routers:
            for route in router.routes:
                m = route.regex.match(path)
                if m:
                    path_matched = True
                    if route.method == method:
                        return route, {k: unquote(v) for k, v in m.groupdict().items()}, True
        return None, {}, path_matched

    async def handle(self, request: Request) -> Response:
        request.app = self  # handlers that introspect the route table (docs)
        tracer = self.state.get("tracer")
        if tracer is None:
            return self._apply_response_hooks(
                request, await self._dispatch(request)
            )
        # Span name uses the route *pattern* — bounded cardinality: raw
        # paths would let unauthenticated garbage requests grow the stats
        # table without limit. One route lookup, shared with _dispatch.
        import time as _time

        match = self._find_route(request.method, request.path)
        route = match[0]
        name = f"http {request.method} {route.pattern if route else '<unmatched>'}"
        start = _time.monotonic()
        resp = await self._dispatch(request, match)
        tracer.record(
            name,
            _time.monotonic() - start,
            error_name=f"http_{resp.status}" if resp.status >= 500 else None,
            status=resp.status,
        )
        return self._apply_response_hooks(request, resp)

    async def _dispatch(self, request: Request, match=None) -> Response:
        try:
            for mw in self.middleware:
                resp = await mw(request)
                if resp is not None:
                    return resp
            route, params, path_matched = (
                match if match is not None
                else self._find_route(request.method, request.path)
            )
            if route is None:
                if path_matched:
                    return Response({"detail": "Method not allowed"}, status=405)
                return Response({"detail": "Not found"}, status=404)
            request.path_params = params
            result = await route.handler(request, **params)
            if isinstance(result, Response):
                return result
            return Response(result)
        except ApiError as e:
            return Response(e.to_json(), status=e.status)
        except ConfigurationError as e:
            # Invalid user YAML/spec nested inside a request body (e.g. a bad
            # `tpu:` accelerator type) is the client's error, not a 500.
            return Response(
                {"detail": [{"msg": str(e), "code": "configuration_error"}]},
                status=400,
            )
        except ValidationError as e:
            return Response(
                {"detail": [{"msg": str(e), "code": "validation_error"}]}, status=400
            )
        except Exception as e:
            logger.exception("Unhandled server error: %s %s", request.method, request.path)
            tracer = self.state.get("tracer")
            if tracer is not None:
                # Sentry-equivalent capture: fingerprinted in /debug/errors.
                tracer.capture_exception(e, method=request.method, path=request.path)
            return Response(
                {"detail": [{"msg": "Internal server error", "code": "server_error"}]},
                status=500,
            )

    _started = False

    async def startup(self) -> None:
        # Idempotent: Server.start() calls this too, and running the hooks
        # twice re-initializes state (an in-memory DB would be wiped).
        if self._started:
            return
        self._started = True
        for fn in self.on_startup:
            await fn()

    async def shutdown(self) -> None:
        if not self._started:
            return
        self._started = False
        for fn in self.on_shutdown:
            await fn()


class WebSocket:
    """Server side of an accepted RFC6455 connection (no extensions)."""

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self._reader = reader
        self._writer = writer
        self.closed = False

    async def send_text(self, data: str) -> None:
        await self._send_frame(0x1, data.encode())

    async def send_bytes(self, data: bytes) -> None:
        await self._send_frame(0x2, data)

    async def ping(self) -> None:
        """Liveness probe for idle streams: a dead peer surfaces as a write
        error within a probe round or two, flipping `closed`."""
        try:
            await self._send_frame(0x9, b"")
        except (ConnectionError, OSError):
            self.closed = True

    async def _send_frame(self, opcode: int, payload: bytes) -> None:
        if self.closed:
            return
        header = bytes([0x80 | opcode])
        n = len(payload)
        if n < 126:
            header += bytes([n])
        elif n < (1 << 16):
            header += bytes([126]) + struct.pack(">H", n)
        else:
            header += bytes([127]) + struct.pack(">Q", n)
        self._writer.write(header + payload)
        await self._writer.drain()

    async def receive(self) -> Optional[bytes]:
        """Next data frame payload, or None when the peer closes."""
        while True:
            try:
                head = await self._reader.readexactly(2)
            except (asyncio.IncompleteReadError, ConnectionError):
                self.closed = True
                return None
            opcode = head[0] & 0x0F
            masked = head[1] & 0x80
            n = head[1] & 0x7F
            if n == 126:
                n = struct.unpack(">H", await self._reader.readexactly(2))[0]
            elif n == 127:
                n = struct.unpack(">Q", await self._reader.readexactly(8))[0]
            mask = await self._reader.readexactly(4) if masked else b"\x00" * 4
            payload = await self._reader.readexactly(n)
            if masked:
                payload = bytes(b ^ mask[i % 4] for i, b in enumerate(payload))
            if opcode == 0x8:  # close
                self.closed = True
                try:
                    await self._send_frame(0x8, b"")
                except ConnectionError:
                    pass
                return None
            if opcode == 0x9:  # ping
                await self._send_frame(0xA, payload)
                continue
            if opcode in (0x1, 0x2, 0x0):
                return payload

    async def close(self) -> None:
        if not self.closed:
            self.closed = True
            try:
                await self._send_frame(0x8, b"")
            except ConnectionError:
                pass


_WS_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"


def _ws_accept_key(key: str) -> str:
    return base64.b64encode(hashlib.sha1((key + _WS_GUID).encode()).digest()).decode()


class Server:
    """asyncio socket server speaking HTTP/1.1 for an `App`."""

    def __init__(self, app: App, host: str = "127.0.0.1", port: int = 3000):
        self.app = app
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> None:
        await self.app.startup()
        self._server = await asyncio.start_server(self._client, self.host, self.port)
        if self.port == 0:
            self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        await self.app.shutdown()

    async def serve_forever(self) -> None:
        await self.start()
        assert self._server is not None
        async with self._server:
            await self._server.serve_forever()

    async def _client(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        try:
            while True:
                request = await self._read_request(reader)
                if request is None:
                    break
                # WebSocket upgrade?
                if request.headers.get("upgrade", "").lower() == "websocket":
                    await self._handle_websocket(request, reader, writer)
                    break
                response = await self.app.handle(request)
                keep_alive = request.headers.get("connection", "").lower() != "close"
                await self._write_response(writer, response, keep_alive)
                if not keep_alive:
                    break
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        except Exception:
            logger.debug("connection handler error:\n%s", traceback.format_exc())
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    async def _read_request(self, reader: asyncio.StreamReader) -> Optional[Request]:
        try:
            request_line = await reader.readline()
        except (ConnectionError, asyncio.LimitOverrunError):
            return None
        if not request_line:
            return None
        try:
            method, target, _version = request_line.decode("latin1").strip().split(" ", 2)
        except ValueError:
            return None
        headers: Dict[str, str] = {}
        total = 0
        while True:
            line = await reader.readline()
            total += len(line)
            if total > MAX_HEADER:
                return None
            if line in (b"\r\n", b"\n", b""):
                break
            name, _, value = line.decode("latin1").partition(":")
            headers[name.strip().lower()] = value.strip()
        body = b""
        if "content-length" in headers:
            n = int(headers["content-length"])
            if n > MAX_BODY:
                return None
            body = await reader.readexactly(n)
        elif headers.get("transfer-encoding", "").lower() == "chunked":
            chunks = []
            while True:
                size_line = await reader.readline()
                size = int(size_line.strip().split(b";")[0], 16)
                if size == 0:
                    await reader.readline()
                    break
                chunks.append(await reader.readexactly(size))
                await reader.readexactly(2)  # trailing CRLF
            body = b"".join(chunks)
        path, _, query_string = target.partition("?")
        return Request(
            method=method.upper(),
            path=unquote(path),
            query=parse_qs(query_string),
            headers=headers,
            body=body,
        )

    async def _write_response(
        self, writer: asyncio.StreamWriter, response: Response, keep_alive: bool
    ) -> None:
        status_text = {200: "OK", 400: "Bad Request", 401: "Unauthorized", 403: "Forbidden",
                       404: "Not Found", 405: "Method Not Allowed", 409: "Conflict",
                       500: "Internal Server Error"}.get(response.status, "")
        lines = [f"HTTP/1.1 {response.status} {status_text}"]
        headers = dict(response.headers)
        if response.stream is None:
            headers["content-length"] = str(len(response.body))
        else:
            headers["transfer-encoding"] = "chunked"
        headers["connection"] = "keep-alive" if keep_alive else "close"
        for k, v in headers.items():
            lines.append(f"{k}: {v}")
        writer.write(("\r\n".join(lines) + "\r\n\r\n").encode())
        if response.stream is None:
            writer.write(response.body)
            await writer.drain()
        else:
            async for chunk in response.stream:
                if chunk:
                    writer.write(f"{len(chunk):x}\r\n".encode() + chunk + b"\r\n")
                    await writer.drain()
            writer.write(b"0\r\n\r\n")
            await writer.drain()

    async def _handle_websocket(
        self, request: Request, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        route, params, _ = self.app._find_route("GET", request.path)
        if route is None or not route.websocket:
            await self._write_response(writer, Response({"detail": "Not found"}, status=404), False)
            return
        # Middleware (ctx injection, auth hooks) runs before the upgrade; a
        # middleware response rejects the handshake with that response.
        for mw in self.app.middleware:
            resp = await mw(request)
            if resp is not None:
                await self._write_response(writer, resp, False)
                return
        key = request.headers.get("sec-websocket-key", "")
        accept = _ws_accept_key(key)
        writer.write(
            (
                "HTTP/1.1 101 Switching Protocols\r\n"
                "Upgrade: websocket\r\n"
                "Connection: Upgrade\r\n"
                f"Sec-WebSocket-Accept: {accept}\r\n\r\n"
            ).encode()
        )
        await writer.drain()
        request.path_params = params
        ws = WebSocket(reader, writer)
        try:
            await route.handler(request, ws, **params)
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            await ws.close()


class TestClient:
    """In-process client: drives `App.handle` directly (no sockets needed)."""

    __test__ = False  # not a pytest collection target despite the name

    def __init__(self, app: App, token: Optional[str] = None):
        self.app = app
        self.token = token

    async def request(
        self,
        method: str,
        path: str,
        json_body: Any = None,
        body: Optional[bytes] = None,
        headers: Optional[Dict[str, str]] = None,
        token: Optional[str] = None,
    ) -> Response:
        hdrs = {k.lower(): v for k, v in (headers or {}).items()}
        tok = self.token if token is None else token  # explicit "" = unauthenticated
        if tok and "authorization" not in hdrs:
            hdrs["authorization"] = f"Bearer {tok}"
        if json_body is not None:
            body = json.dumps(json_body, default=_json_default).encode()
            hdrs["content-type"] = "application/json"
        path_only, _, qs = path.partition("?")
        req = Request(
            method=method.upper(),
            path=path_only,
            query=parse_qs(qs),
            headers=hdrs,
            body=body or b"",
        )
        return await self.app.handle(req)

    async def post(self, path: str, json_body: Any = None, **kw) -> Response:
        return await self.request("POST", path, json_body=json_body, **kw)

    async def get(self, path: str, **kw) -> Response:
        return await self.request("GET", path, **kw)


def response_json(resp: Response) -> Any:
    return json.loads(resp.body) if resp.body else None
