"""In-process tracing, error capture, and statistical profiling.

Parity: the reference wires Sentry (tracing + profiling sample rates,
server/app.py:68-76) and imports net/http/pprof in the Go runner
(runner/cmd/runner/main.go:7). This environment has zero egress, so the
equivalent is self-hosted: a span recorder with per-name latency stats, an
error ring with Sentry-style fingerprint dedupe, and a sampling profiler
over `sys._current_frames` that emits collapsed stacks (flamegraph
format). Everything is stdlib and lock-cheap; exposed over /debug/*
(routers/debug.py) the way pprof exposes /debug/pprof/*.
"""

import bisect
import itertools
import sys
import threading
import time
import traceback
from collections import Counter, defaultdict, deque
from contextlib import contextmanager
from typing import Any, Dict, List, Optional

_span_ids = itertools.count(1)

# Fixed log-spaced histogram buckets (seconds): 1 ms .. ~69 min doubling,
# 23 finite buckets + implicit +Inf. One shared ladder for every duration
# histogram (stage latencies, TTFT/TTFB) keeps exposition size bounded and
# lets quantile queries aggregate across series.
LOG_BUCKETS: tuple = tuple(0.001 * (2 ** i) for i in range(23))


class HistogramData:
    """One labelled histogram series: per-bucket counts + sum + count.

    `counts[i]` is the NON-cumulative count of observations in bucket i
    (<= LOG_BUCKETS[i]); the last slot is the +Inf overflow. Snapshots
    compute the cumulative `le` form Prometheus expects."""

    __slots__ = ("buckets", "counts", "sum", "count")

    def __init__(self, buckets: tuple = LOG_BUCKETS):
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        idx = bisect.bisect_left(self.buckets, value)
        self.counts[idx] += 1
        self.sum += value
        self.count += 1

    def to_dict(self) -> Dict[str, Any]:
        cumulative = []
        running = 0
        for le, n in zip(self.buckets, self.counts):
            running += n
            cumulative.append((le, running))
        return {
            "buckets": cumulative,  # [(le_seconds, cumulative_count), ...]
            "sum": self.sum,
            "count": self.count,
        }


class SpanStats:
    __slots__ = ("count", "total_s", "max_s", "errors")

    def __init__(self):
        self.count = 0
        self.total_s = 0.0
        self.max_s = 0.0
        self.errors = 0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "count": self.count,
            "errors": self.errors,
            "total_s": round(self.total_s, 6),
            "avg_ms": round(self.total_s / self.count * 1000, 3) if self.count else 0.0,
            "max_ms": round(self.max_s * 1000, 3),
        }


class Tracer:
    """Span recorder: recent spans in a ring, aggregates per span name."""

    def __init__(self, max_spans: int = 1000, max_errors: int = 200):
        self._lock = threading.Lock()
        self.spans: deque = deque(maxlen=max_spans)
        self.stats: Dict[str, SpanStats] = defaultdict(SpanStats)
        # Monotonic labelled counters (resilience events, chaos injections):
        # name -> {sorted-label-tuple: value}. Exposed on /metrics in
        # Prometheus text format and in /debug/traces snapshots.
        self.counters: Dict[str, Dict[tuple, float]] = defaultdict(dict)
        # Labelled histograms (stage latencies, TTFT): name ->
        # {sorted-label-tuple: HistogramData}. Same keying as counters;
        # exposed on /metrics as _bucket/_sum/_count.
        self.histograms: Dict[str, Dict[tuple, HistogramData]] = defaultdict(dict)
        # Sentry-style error dedupe: fingerprint -> {first/last seen, count,
        # one representative traceback}.
        self.errors: Dict[str, Dict[str, Any]] = {}
        self._errors_order: deque = deque(maxlen=max_errors)

    def inc(self, name: str, value: float = 1, **labels: Any) -> None:
        """Bump a labelled counter (monotonic; create-on-first-use)."""
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            series = self.counters[name]
            series[key] = series.get(key, 0) + value

    def counter_snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [
                {"name": name, "labels": dict(key), "value": value}
                for name, series in self.counters.items()
                for key, value in series.items()
            ]

    def observe(self, name: str, value: float, **labels: Any) -> None:
        """Record one observation into a labelled histogram (log-spaced
        buckets, create-on-first-use)."""
        key = tuple(sorted((k, str(v)) for k, v in labels.items()))
        with self._lock:
            series = self.histograms[name]
            hist = series.get(key)
            if hist is None:
                hist = series[key] = HistogramData()
            hist.observe(value)

    def histogram_snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [
                {"name": name, "labels": dict(key), **hist.to_dict()}
                for name, series in self.histograms.items()
                for key, hist in series.items()
            ]

    def record(
        self,
        name: str,
        duration_s: float,
        error_name: Optional[str] = None,
        **attrs: Any,
    ) -> None:
        """Record one completed span (for callers that time manually, e.g.
        the HTTP layer which also wants the response status as an attr)."""
        with self._lock:
            st = self.stats[name]
            st.count += 1
            st.total_s += duration_s
            st.max_s = max(st.max_s, duration_s)
            if error_name is not None:
                st.errors += 1
            self.spans.append({
                "id": next(_span_ids),
                "name": name,
                "ts": time.time(),
                "duration_ms": round(duration_s * 1000, 3),
                "error": error_name,
                **attrs,
            })

    @contextmanager
    def span(self, name: str, **attrs: Any):
        start = time.monotonic()
        error: Optional[BaseException] = None
        try:
            yield
        except BaseException as e:
            error = e
            raise
        finally:
            # CancelledError/KeyboardInterrupt are control flow (clean
            # shutdown cancels every background span) — time them, but do
            # not count them as errors or pollute /debug/errors.
            is_failure = isinstance(error, Exception)
            self.record(
                name,
                time.monotonic() - start,
                error_name=type(error).__name__ if is_failure else None,
                **attrs,
            )
            if is_failure:
                self.capture_exception(error, span=name, **attrs)

    def capture_exception(self, exc: BaseException, **context: Any) -> str:
        """Record an exception event; returns its fingerprint. Repeats of the
        same (type, raise site) bump a counter instead of flooding the ring."""
        tb = exc.__traceback__
        site = ""
        while tb is not None:  # innermost frame = the raise site
            site = f"{tb.tb_frame.f_code.co_filename}:{tb.tb_lineno}"
            tb = tb.tb_next
        fingerprint = f"{type(exc).__name__}@{site}"
        now = time.time()
        with self._lock:
            ev = self.errors.get(fingerprint)
            if ev is None:
                if len(self._errors_order) == self._errors_order.maxlen:
                    oldest = self._errors_order.popleft()
                    self.errors.pop(oldest, None)
                self._errors_order.append(fingerprint)
                self.errors[fingerprint] = {
                    "fingerprint": fingerprint,
                    "type": type(exc).__name__,
                    "message": str(exc)[:500],
                    "first_seen": now,
                    "last_seen": now,
                    "count": 1,
                    "traceback": "".join(
                        traceback.format_exception(type(exc), exc, exc.__traceback__)
                    )[-4000:],
                    "context": {k: str(v)[:200] for k, v in context.items()},
                }
            else:
                ev["count"] += 1
                ev["last_seen"] = now
                ev["message"] = str(exc)[:500]
        return fingerprint

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "stats": {name: st.to_dict() for name, st in self.stats.items()},
                "counters": [
                    {"name": name, "labels": dict(key), "value": value}
                    for name, series in self.counters.items()
                    for key, value in series.items()
                ],
                "recent_spans": list(self.spans)[-100:],
            }

    def stats_snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Per-span aggregates only. The /metrics scrape path wants just
        these; `snapshot()` also copies the full span ring (up to 1000
        dicts) per call, which is pure waste at scrape frequency."""
        with self._lock:
            return {name: st.to_dict() for name, st in self.stats.items()}

    def error_snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return sorted(
                (dict(e) for e in self.errors.values()),
                key=lambda e: e["last_seen"],
                reverse=True,
            )


def thread_dump() -> Dict[str, List[str]]:
    """Stacks of every live thread (pprof `goroutine`-profile equivalent)."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out: Dict[str, List[str]] = {}
    for ident, frame in sys._current_frames().items():
        label = f"{names.get(ident, 'unknown')}-{ident}"
        out[label] = [
            f"{fs.filename}:{fs.lineno} {fs.name}"
            for fs in traceback.extract_stack(frame)
        ]
    return out


def sample_profile(seconds: float = 2.0, hz: int = 100) -> Dict[str, Any]:
    """Statistical profile: sample all thread stacks at `hz` for `seconds`,
    return collapsed stacks ("frame;frame;frame count" — flamegraph.pl /
    speedscope input) sorted by weight. The pprof `profile` equivalent,
    pure stdlib, safe to run against a live server."""
    interval = 1.0 / hz
    counts: Counter = Counter()
    samples = 0
    start = time.monotonic()
    deadline = start + seconds
    # Next-deadline pacing: sleeping a flat `interval` after each walk adds
    # the walk cost (which grows with thread count and stack depth) to every
    # period, so the effective rate drifts well below `hz` exactly on the
    # busy servers worth profiling. Anchoring each wakeup to start+k/hz
    # absorbs walk cost into the sleep; a walk slower than one period skips
    # ahead instead of building a backlog of zero-sleep samples.
    next_at = start
    now = start
    while now < deadline:
        for frame in sys._current_frames().values():
            # Raw frame walk — traceback.extract_stack touches linecache
            # (file IO) and is far too slow to sample at 100 Hz.
            parts: List[str] = []
            f = frame
            while f is not None:
                code = f.f_code
                parts.append(
                    f"{code.co_name} ({code.co_filename.rsplit('/', 1)[-1]}:{f.f_lineno})"
                )
                f = f.f_back
            counts[";".join(reversed(parts))] += 1
        samples += 1
        now = time.monotonic()
        next_at += interval
        if next_at < now:  # walk overran the period: realign, don't burst
            next_at = now
        elif next_at < deadline:
            time.sleep(next_at - now)
            now = time.monotonic()
        else:
            break
    elapsed = max(time.monotonic() - start, 1e-9)
    return {
        "seconds": seconds,
        "hz": hz,
        "samples": samples,
        "effective_hz": round(samples / elapsed, 3),
        "collapsed": [
            {"stack": stack, "count": n} for stack, n in counts.most_common(200)
        ],
    }


