"""Device-mesh planning helpers for workloads launched by the orchestrator.

Maps a TpuTopology (physical: hosts x chips-per-host over ICI) to logical
`jax.sharding.Mesh` axis layouts for common parallelism styles (dp/fsdp/tp).
This is the orchestrator-side planner (offer display, docs, sanity checks);
`plan_mesh`'s `{axis: size}` output feeds
`dstack_tpu.workloads.sharding.make_mesh`, which builds the actual Mesh
inside a job. User code is free to build its own mesh — every chip in a
slice is ICI-connected.
"""

from typing import Dict, Optional, Sequence, Tuple

from dstack_tpu.models.topology import TpuTopology


def plan_mesh(
    topo: TpuTopology,
    tensor_parallel: Optional[int] = None,
    fsdp: Optional[int] = None,
) -> Dict[str, int]:
    """Plan `{axis: size}` for a slice.

    Defaults: tensor-parallel axis = chips per host (stays on one host's
    ICI-contiguous chips, where all-reduce latency is lowest); remaining
    factor is (fs)dp across hosts — the layout the scaling-book recipe
    starts from.
    """
    chips = topo.chips
    tp = tensor_parallel or topo.chips_per_host
    if chips % tp != 0:
        raise ValueError(f"tensor_parallel={tp} does not divide {chips} chips")
    rest = chips // tp
    if fsdp is None:
        fsdp = rest
    if fsdp == 0 or rest % fsdp != 0:
        raise ValueError(f"fsdp={fsdp} does not divide {rest}")
    dp = rest // fsdp
    axes = {"data": dp, "fsdp": fsdp, "model": tp}
    return {k: v for k, v in axes.items() if v > 1} or {"data": 1}


def mesh_shape_for_devices(
    n_devices: int, tensor_parallel: int = 1
) -> Tuple[Tuple[int, ...], Tuple[str, ...]]:
    """(shape, axis_names) for `jax.sharding.Mesh` over n flat devices."""
    if n_devices % tensor_parallel != 0:
        raise ValueError(f"{tensor_parallel=} does not divide {n_devices=}")
    return (n_devices // tensor_parallel, tensor_parallel), ("data", "model")


def rescale_accum_steps(accum_steps: int, old_width: int, new_width: int) -> int:
    """Gradient-accumulation steps after an elastic data-parallel resize,
    preserving the global batch: accum_steps x dp_width is invariant, so the
    loss trajectory (and LR schedule) is bit-compatible with the full-width
    run. Raises when the global step count does not divide evenly at the new
    width — the caller must then choose a different microbatch split rather
    than silently training at a different batch size.

    Rounding contract: there is NONE. The result is always the exact
    integer `accum_steps * old_width / new_width`; widths where that
    quotient is not an integer raise ValueError rather than rounding in
    either direction (floor would shrink the global batch, ceil would
    grow it — both silently change the effective batch size and detach
    the loss trajectory from the full-width run). The same invariant
    backs actor-gang resize in the RL workload (workloads/rl.py), where
    accum-per-actor x gang_width keeps trajectories-per-update fixed.
    Both arguments must be positive; zero and negative widths raise.
    Identity resizes (old_width == new_width) always succeed and return
    accum_steps unchanged.
    """
    if old_width <= 0 or new_width <= 0:
        raise ValueError(f"mesh widths must be positive, got {old_width}->{new_width}")
    total = accum_steps * old_width
    if total % new_width != 0:
        raise ValueError(
            f"global batch of {total} microbatches does not divide evenly"
            f" across dp width {new_width}; pick accum_steps so that"
            f" accum_steps * width is divisible by every width you may"
            f" shrink to"
        )
    return total // new_width
