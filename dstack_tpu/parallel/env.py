"""JAX distributed bootstrap env assembly — the TPU-native replacement for the
reference's `DSTACK_MASTER_NODE_IP` / `MASTER_ADDR` + NCCL env injection
(runner/internal/executor/executor.go:213-230, SURVEY §2.7).

The orchestrator's contract with the container is pure environment:

  Single-slice (ICI) pod run, one process per worker host:
    JAX_COORDINATOR_ADDRESS = <master ip>:<port>       (jax.distributed)
    JAX_PROCESS_ID          = <host rank in slice>
    JAX_NUM_PROCESSES       = <hosts in slice>
    PJRT_DEVICE             = TPU
    TPU_WORKER_ID           = <host rank>              (libtpu)
    TPU_WORKER_HOSTNAMES    = ip0,ip1,...              (libtpu)

  Multi-slice (DCN) runs additionally get MEGASCALE_* so XLA stitches
  slices over the data-center network.

  DSTACK_* vars are kept for compatibility with the reference's examples
  (e.g. scripts branching on DSTACK_NODE_RANK).

`jax.distributed.initialize()` with no args consumes exactly these variables,
so user code needs zero bootstrap logic.
"""

from typing import Dict, List, Optional

from dstack_tpu.models.runs import ClusterInfo

DEFAULT_COORDINATOR_PORT = 8476
DEFAULT_MEGASCALE_PORT = 8576
# Weight-refresh channel for Podracer RL gangs (workloads/rl.py): the
# learner binds its WeightRefreshServer on the master host at this
# port; actor processes on every other host read the address from env.
DEFAULT_RL_REFRESH_PORT = 8676


def make_cluster_env(
    cluster: ClusterInfo,
    node_rank: int,
) -> Dict[str, str]:
    """Env for one worker host of a gang-scheduled run."""
    n = len(cluster.job_ips)
    coordinator = f"{cluster.master_job_ip}:{cluster.coordinator_port}"
    env = {
        # JAX-native bootstrap (jax.distributed.initialize reads these).
        "JAX_COORDINATOR_ADDRESS": coordinator,
        "JAX_COORDINATOR_PORT": str(cluster.coordinator_port),
        "JAX_PROCESS_ID": str(node_rank),
        "JAX_NUM_PROCESSES": str(n),
        "PJRT_DEVICE": "TPU",
        # libtpu topology discovery for multi-host slices.
        "TPU_WORKER_ID": str(node_rank),
        "TPU_WORKER_HOSTNAMES": ",".join(cluster.job_ips),
        # Reference-compatible vars so existing example scripts keep working
        # (reference: executor.go:219-230).
        "DSTACK_NODES_IPS": "\n".join(cluster.job_ips),
        "DSTACK_MASTER_NODE_IP": cluster.master_job_ip,
        "DSTACK_NODE_RANK": str(node_rank),
        "DSTACK_NODES_NUM": str(n),
        "DSTACK_GPUS_PER_NODE": str(cluster.chips_per_host),
        "DSTACK_GPUS_NUM": str(cluster.chips_per_host * n),
        # Chips-first aliases.
        "DSTACK_CHIPS_PER_HOST": str(cluster.chips_per_host),
        "DSTACK_CHIPS_NUM": str(cluster.chips_per_host * n),
        # RL actor/learner gangs (workloads/rl.py): where actors pull
        # fresh policy weights from. Harmless for non-RL workloads —
        # nothing binds the port unless an RL learner starts.
        "DSTACK_TPU_RL_REFRESH_ADDR":
            f"{cluster.master_job_ip}:{DEFAULT_RL_REFRESH_PORT}",
    }
    if cluster.tpu_slice is not None:
        env["DSTACK_TPU_ACCELERATOR_TYPE"] = cluster.tpu_slice.accelerator_type
        env["DSTACK_TPU_TOPOLOGY"] = cluster.tpu_slice.topology_string
    if cluster.slice_count > 1:
        env.update(make_megascale_env(cluster))
    return env


def make_elastic_env(
    cluster: ClusterInfo,
    node_rank: int,
    active_ranks: List[int],
) -> Dict[str, str]:
    """Coordinator env for a SURVIVOR of an elastic resize.

    When a worker host is preempted out of an elastic data-parallel gang,
    the remaining hosts re-form the JAX process group at reduced width:
    process ids must stay dense (0..n-1) and the hostname list must shrink
    to the live hosts, or `jax.distributed.initialize` hangs waiting for
    the dead rank. This derives that env from the original ClusterInfo plus
    the set of surviving node ranks — the server pushes it through the
    runner's resize channel, the trainer re-initializes from its last
    checkpoint (see docs/guides/resilience.md, "Elastic training").

    The coordinator host must survive (rank 0 is never elastically removed
    — the FSM only resizes around non-coordinator ranks).
    """
    ranks = sorted(active_ranks)
    if node_rank not in ranks:
        raise ValueError(f"node_rank {node_rank} is not among survivors {ranks}")
    if 0 not in ranks:
        raise ValueError("elastic resize cannot remove the coordinator (rank 0)")
    ips = [cluster.job_ips[r] for r in ranks]
    shrunk = cluster.model_copy(update={"job_ips": ips})
    return make_cluster_env(shrunk, ranks.index(node_rank))


def make_megascale_env(cluster: ClusterInfo) -> Dict[str, str]:
    """Multi-slice (DCN) env: XLA's megascale runtime coordinates slices.

    `MEGASCALE_COORDINATOR_ADDRESS` must be the same host for every process
    in every slice; slice 0's master is used.
    """
    return {
        "MEGASCALE_COORDINATOR_ADDRESS": f"{cluster.master_job_ip}:{DEFAULT_MEGASCALE_PORT}",
        "MEGASCALE_NUM_SLICES": str(cluster.slice_count),
        "MEGASCALE_SLICE_ID": str(cluster.slice_id),
    }


def jax_initialize_kwargs(env: Dict[str, str]) -> Dict[str, object]:
    """The `jax.distributed.initialize(**kwargs)` equivalent of the env —
    used by docs/tests to assert the env is sufficient and consistent."""
    return {
        "coordinator_address": env["JAX_COORDINATOR_ADDRESS"],
        "num_processes": int(env["JAX_NUM_PROCESSES"]),
        "process_id": int(env["JAX_PROCESS_ID"]),
    }
