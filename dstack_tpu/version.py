__version__ = "0.1.0"

# Minimum client version the server accepts; used by the version-check
# middleware (reference: src/dstack/_internal/server/app.py middleware).
MIN_CLIENT_VERSION = "0.1.0"
