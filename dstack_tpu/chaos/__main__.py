"""Headless chaos runner: `python -m dstack_tpu.chaos --scenario NAME`.

Boots an in-memory server with the local backend, runs the named chaos
scenario (see `dstack_tpu/chaos/scenarios.py`), prints the report, and
exits nonzero if any expectation failed — wire it into CI the same way as
`make chaos`.
"""

import argparse
import asyncio
import json
import sys

from dstack_tpu.chaos.scenarios import list_scenarios, run_scenario


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m dstack_tpu.chaos",
        description="Run deterministic chaos/resilience scenarios headlessly.",
    )
    parser.add_argument("--scenario", "-s", help="scenario name (see --list)")
    parser.add_argument("--seed", type=int, default=0, help="fault-injection seed")
    parser.add_argument("--all", action="store_true", help="run every scenario")
    parser.add_argument("--list", action="store_true", help="list scenarios and exit")
    parser.add_argument("--json", action="store_true", help="emit raw JSON reports")
    args = parser.parse_args(argv)

    if args.list:
        for name in list_scenarios():
            print(name)
        return 0
    names = list_scenarios() if args.all else ([args.scenario] if args.scenario else [])
    if not names:
        parser.error("pass --scenario NAME, --all, or --list")

    ok = True
    for name in names:
        report = asyncio.run(run_scenario(name, seed=args.seed))
        if args.json:
            print(json.dumps(report))
        else:
            status = "PASS" if report["ok"] else "FAIL"
            print(f"[{status}] {name} (seed {report['seed']})")
            for f in report["failures"]:
                print(f"  - {f}")
            for k, v in report.get("details", {}).items():
                print(f"  {k}: {v}")
        ok = ok and report["ok"]
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
