"""Seeded deterministic fault-injection engine.

The engine holds a declarative schedule of `ChaosEvent`s and is consulted
from hook points wired through the serving path:

  - ``runner.http`` / ``shim.http`` — every agent-client request
    (`server/services/runner/client.py`): drop (error) or delay (latency)
    heartbeats and any other agent call.
  - ``gcp.api`` — every `GcpApi.request` (`backends/gcp/api.py`): inject
    backend-API errors/latency.
  - ``tick`` — the engine's own logical clock: `preempt` (write the
    maintenance-event file the agent-side watcher polls) and `crash`
    (SIGKILL a registered runner process — a reclaimed VM with no notice).

Determinism: call-scheduled events fire on the Nth *matching* call
(per-event counters, no wall clock); probability-gated events draw from one
`random.Random(seed)`, so a (schedule, seed) pair replays identically.
Tick-scheduled events run on a logical tick counter and can be gated on a
filesystem path (`when_path_exists`) to synchronize with workload progress
markers — state-based, not time-based, so scenarios stay reproducible on
loaded CI hosts.

Everything injected is recorded in `engine.injected` for assertions and
scenario reports.
"""

import asyncio
import logging
import os
import random
import signal
from pathlib import Path
from typing import Any, Dict, List, Optional, Union

from dstack_tpu.models.common import CoreModel

logger = logging.getLogger(__name__)


class ChaosError(Exception):
    """An injected fault. Hook sites translate it into the error type their
    layer already handles (AgentHTTPError, GcpApiError) so downstream FSM
    code cannot tell chaos from the real failure it simulates."""

    def __init__(self, message: str = "chaos: injected fault", status: int = 503):
        super().__init__(message)
        self.status = status


class ChaosEvent(CoreModel):
    """One schedule entry. Call-hook events (`error`/`latency`) fire on
    matching calls; tick events (`preempt`/`crash`) fire from the engine's
    tick loop against registered workers."""

    hook: str  # "runner.http" | "shim.http" | "gcp.api" | "tick"
    action: str = "error"  # error | latency | preempt | crash
    # Substring filters on the hook call's attrs, e.g. {"path": "/api/pull"}.
    match: Dict[str, str] = {}
    # Call scheduling: fire from the Nth matching call (1-based; default 1)
    # for `calls` consecutive matches (None = unlimited).
    at_call: Optional[int] = None
    calls: Optional[int] = 1
    # Seeded coin per otherwise-due call (composes with at_call/calls).
    probability: Optional[float] = None
    # Tick scheduling (preempt/crash): earliest logical tick, and/or a
    # progress gate — the event waits until this path exists.
    at_tick: Optional[int] = None
    when_path_exists: Optional[str] = None
    once: bool = True
    # Target selectors for preempt/crash (None = every registered worker).
    worker: Optional[int] = None
    instance: Optional[str] = None
    # Fault parameters.
    latency_s: float = 0.0
    status: int = 503
    message: str = "chaos: injected fault"


class ChaosEngine:
    def __init__(
        self,
        schedule: List[Union[ChaosEvent, Dict[str, Any]]],
        seed: int = 0,
        tick_interval: float = 0.25,
        name: str = "chaos",
    ):
        self.name = name
        self.seed = seed
        self.rng = random.Random(seed)
        self.events = [
            e if isinstance(e, ChaosEvent) else ChaosEvent.model_validate(e)
            for e in schedule
        ]
        self.tick_interval = tick_interval
        self.tick = 0
        self.injected: List[Dict[str, Any]] = []  # audit log of fired faults
        self._counts = [0] * len(self.events)  # matching calls seen, per event
        self._fired = [0] * len(self.events)
        self._workers: List[Dict[str, Any]] = []
        self._task: Optional[asyncio.Task] = None
        self._stopped = False

    # -- hook-point API ------------------------------------------------------

    async def inject(self, hook: str, **attrs: Any) -> None:
        """Consulted at a hook point: sleeps for scheduled latency, raises
        ChaosError for a scheduled error. No-op when nothing is due."""
        delay = 0.0
        err: Optional[ChaosEvent] = None
        for i, ev in enumerate(self.events):
            if ev.hook != hook or ev.action not in ("error", "latency"):
                continue
            if not self._matches(ev, attrs):
                continue
            self._counts[i] += 1
            if not self._due(i, ev):
                continue
            self._fired[i] += 1
            self._record(ev, hook=hook, **attrs)
            if ev.action == "latency":
                delay = max(delay, ev.latency_s)
            else:
                err = ev
        if delay:
            await asyncio.sleep(delay)
        if err is not None:
            raise ChaosError(err.message, err.status)

    def register_worker(
        self,
        instance_name: str,
        worker: int,
        *,
        preemption_file: Optional[str] = None,
        pids: Optional[List[int]] = None,
    ) -> None:
        """Called by the local backend when it spawns a worker host, making
        it a target for tick-scheduled preempt/crash events."""
        self._workers.append(
            {
                "instance": instance_name,
                "worker": worker,
                "preemption_file": preemption_file,
                "pids": pids or [],
            }
        )

    # -- tick loop -----------------------------------------------------------

    async def start(self) -> None:
        if self._task is None:
            self._stopped = False
            self._task = asyncio.get_event_loop().create_task(self._tick_loop())

    async def stop(self) -> None:
        self._stopped = True
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _tick_loop(self) -> None:
        while not self._stopped:
            await asyncio.sleep(self.tick_interval)
            self.tick += 1
            for i, ev in enumerate(self.events):
                if ev.hook != "tick" or ev.action not in ("preempt", "crash"):
                    continue
                if ev.once and self._fired[i]:
                    continue
                if ev.at_tick is not None and self.tick < ev.at_tick:
                    continue
                if ev.when_path_exists and not os.path.exists(ev.when_path_exists):
                    continue
                targets = self._targets(ev)
                if not targets:
                    continue  # nothing registered yet; retry next tick
                self._fired[i] += 1
                for t in targets:
                    if ev.action == "preempt":
                        self._fire_preempt(ev, t)
                    else:
                        self._fire_crash(ev, t)

    def _targets(self, ev: ChaosEvent) -> List[Dict[str, Any]]:
        out = []
        for t in self._workers:
            if ev.worker is not None and t["worker"] != ev.worker:
                continue
            if ev.instance is not None and ev.instance not in t["instance"]:
                continue
            out.append(t)
        return out

    def _fire_preempt(self, ev: ChaosEvent, target: Dict[str, Any]) -> None:
        path = target.get("preemption_file")
        if not path:
            return
        Path(path).parent.mkdir(parents=True, exist_ok=True)
        Path(path).write_text("TERMINATE_ON_HOST_MAINTENANCE")
        self._record(ev, hook="tick", **{k: target[k] for k in ("instance", "worker")})
        logger.info(
            "chaos: preemption notice for %s worker %s", target["instance"], target["worker"]
        )

    def _fire_crash(self, ev: ChaosEvent, target: Dict[str, Any]) -> None:
        self._record(ev, hook="tick", **{k: target[k] for k in ("instance", "worker")})
        for pid in target["pids"]:
            try:
                os.killpg(os.getpgid(pid), signal.SIGKILL)
                logger.info("chaos: crashed runner pid %s (worker %s)", pid, target["worker"])
            except (ProcessLookupError, PermissionError):
                pass

    # -- internals -----------------------------------------------------------

    def _matches(self, ev: ChaosEvent, attrs: Dict[str, Any]) -> bool:
        return all(needle in str(attrs.get(key, "")) for key, needle in ev.match.items())

    def _due(self, i: int, ev: ChaosEvent) -> bool:
        if ev.when_path_exists and not os.path.exists(ev.when_path_exists):
            return False
        first = ev.at_call or 1
        n = self._counts[i]
        if n < first:
            return False
        if ev.calls is not None and n >= first + ev.calls:
            return False
        if ev.probability is not None and self.rng.random() >= ev.probability:
            return False
        return True

    def _record(self, ev: ChaosEvent, **attrs: Any) -> None:
        self.injected.append(
            {
                "tick": self.tick,
                "action": ev.action,
                "message": ev.message,
                **{k: v for k, v in attrs.items() if isinstance(v, (str, int, float))},
            }
        )
