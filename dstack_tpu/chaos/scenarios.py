"""Bundled chaos scenarios: an in-process server + local backend + the chaos
engine, with pass/fail expectations — the headless face of the subsystem
(`python -m dstack_tpu.chaos --scenario NAME`) and the fixture behind the
tier-1 chaos tests.

Each scenario boots a fresh in-memory server with background FSMs running,
installs a seeded `ChaosEngine`, submits a run on the local backend (real
runner subprocesses), and asserts the recovery story end to end. The report
is plain data so the CLI can render it and CI can gate on `ok`.
"""

import asyncio
import os
import tempfile
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional

from dstack_tpu import chaos
from dstack_tpu.chaos.engine import ChaosEngine

REPO_ROOT = str(Path(__file__).resolve().parent.parent.parent)

SCENARIOS: Dict[str, Callable] = {}


def scenario(name: str):
    def deco(fn):
        SCENARIOS[name] = fn
        return fn

    return deco


def list_scenarios() -> List[str]:
    return sorted(SCENARIOS)


async def run_scenario(name: str, seed: int = 0) -> Dict[str, Any]:
    """Run one scenario; returns {name, seed, ok, failures, details}."""
    if name not in SCENARIOS:
        raise ValueError(f"unknown scenario {name!r}; have {list_scenarios()}")
    from dstack_tpu.server import settings

    saved = {
        k: getattr(settings, k)
        for k in ("RETRY_PENDING_RUN_DELAY", "RUNNER_DISCONNECT_GRACE")
    }
    report: Dict[str, Any] = {"name": name, "seed": seed, "failures": [], "details": {}}
    try:
        with tempfile.TemporaryDirectory(prefix=f"dstack-chaos-{name}-") as tmp:
            await SCENARIOS[name](report, seed, Path(tmp))
    finally:
        for k, v in saved.items():
            setattr(settings, k, v)
        chaos.uninstall()
    report["ok"] = not report["failures"]
    return report


def _expect(report: Dict[str, Any], cond: bool, what: str) -> None:
    if not cond:
        report["failures"].append(what)


async def _make_server(
    tpu_sim: Optional[List[str]] = None, **backend_overrides
):
    from dstack_tpu.server.app import create_app
    from dstack_tpu.server.http import TestClient

    app = create_app(db_path=":memory:", run_background_tasks=True)
    await app.startup()
    ctx = app.state["ctx"]
    if tpu_sim or backend_overrides:
        conf = dict(backend_overrides)
        if tpu_sim:
            conf["tpu_sim"] = tpu_sim
        ctx.overrides["local_backend_config"] = conf
    client = TestClient(app, token=app.state["admin_token"])
    return app, ctx, client


async def _wait_run(client, run_name: str, targets, timeout: float):
    from dstack_tpu.server.http import response_json

    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        resp = await client.post(
            "/api/project/main/runs/get", json_body={"run_name": run_name}
        )
        run = response_json(resp)
        if run and run.get("status") in targets:
            return run
        if asyncio.get_event_loop().time() > deadline:
            return run
        await asyncio.sleep(0.2)


def _task_body(commands, run_name, resources=None, retry=None, nodes=1, **conf_extra):
    conf: Dict[str, Any] = {
        "type": "task",
        "commands": commands,
        "nodes": nodes,
        "resources": resources or {"cpu": "1..", "memory": "0.1.."},
        **conf_extra,
    }
    if retry is not None:
        conf["retry"] = retry
    return {
        "run_spec": {
            "run_name": run_name,
            "configuration": conf,
            "ssh_key_pub": "ssh-rsa CHAOS",
        }
    }


# ---- scenarios -------------------------------------------------------------


@scenario("runner-flap")
async def _runner_flap(report, seed, tmp: Path) -> None:
    """Transient agent flakes: two consecutive /api/pull failures injected
    mid-run must be absorbed by the disconnect grace — the run finishes on
    its FIRST submission, no resubmit."""
    from dstack_tpu.server import settings

    settings.RETRY_PENDING_RUN_DELAY = 0
    engine = chaos.install(
        ChaosEngine(
            [
                {
                    "hook": "runner.http",
                    "action": "error",
                    "match": {"path": "/api/pull"},
                    "at_call": 2,
                    "calls": 2,
                    "message": "chaos: dropped heartbeat",
                }
            ],
            seed=seed,
            name="runner-flap",
        )
    )
    app, ctx, client = await _make_server()
    try:
        await engine.start()
        body = _task_body(
            ["sleep 2; echo flap-survived"],
            "chaos-flap",
            retry={"on_events": ["interruption"], "duration": 600},
        )
        resp = await client.post("/api/project/main/runs/submit", json_body=body)
        _expect(report, resp.status == 200, f"submit failed: {resp.body!r}")
        run = await _wait_run(client, "chaos-flap", {"done", "failed", "terminated"}, 60)
        _expect(report, run["status"] == "done", f"run ended {run['status']}, want done")
        subs = run["jobs"][0]["job_submissions"]
        _expect(
            report,
            len(subs) == 1,
            f"{len(subs)} submissions, want 1 (grace should absorb the flap)",
        )
        _expect(
            report,
            len(engine.injected) >= 2,
            f"engine injected {len(engine.injected)} faults, want >= 2",
        )
        report["details"]["injected"] = engine.injected
        report["details"]["submissions"] = len(subs)
    finally:
        await engine.stop()
        await app.shutdown()


@scenario("hard-preempt")
async def _hard_preempt(report, seed, tmp: Path) -> None:
    """A reclaimed VM with no notice: SIGKILL one worker's runner of a
    2-worker gang mid-run. The server must classify the dead agent as an
    interruption, kill the sibling, and resubmit the gang once."""
    from dstack_tpu.server import settings

    settings.RETRY_PENDING_RUN_DELAY = 0
    settings.RUNNER_DISCONNECT_GRACE = 1.0
    started = tmp / "started"
    crash_done = tmp / "crash-done"
    engine = chaos.install(
        ChaosEngine(
            [
                {
                    "hook": "tick",
                    "action": "crash",
                    "worker": 1,
                    "when_path_exists": str(started),
                    "message": "chaos: VM reclaimed",
                }
            ],
            seed=seed,
            name="hard-preempt",
        )
    )
    app, ctx, client = await _make_server(tpu_sim=["v5p-16"])
    try:
        await engine.start()
        # Both ranks check the crash marker ONCE at startup: the first
        # incarnation (marker absent) parks until the server tears it down
        # after the crash; the resubmitted gang (marker present — written
        # below once the injection is observed) finishes fast. Rank 0 also
        # opens the chaos window by touching the `started` gate.
        cmd = (
            f'[ "$JAX_PROCESS_ID" = "0" ] && touch {started};'
            f" if [ -f {crash_done} ]; then sleep 1; echo retried rank done;"
            f" else sleep 300; fi"
        )
        body = _task_body(
            [cmd],
            "chaos-hard",
            resources={"tpu": "v5p-16"},
            retry={"on_events": ["interruption"], "duration": 600},
        )
        resp = await client.post("/api/project/main/runs/submit", json_body=body)
        _expect(report, resp.status == 200, f"submit failed: {resp.body!r}")
        for _ in range(300):  # release the retried gang once the crash fired
            if engine.injected:
                await asyncio.to_thread(crash_done.write_text, "crashed")
                break
            await asyncio.sleep(0.2)
        _expect(report, engine.injected != [], "crash event never fired")
        run = await _wait_run(client, "chaos-hard", {"done", "failed", "terminated"}, 120)
        _expect(report, run["status"] == "done", f"run ended {run['status']}, want done")
        reasons = set()
        for job in run["jobs"]:
            subs = job["job_submissions"]
            _expect(
                report,
                len(subs) == 2,
                f"job {job['job_spec']['job_num']}: {len(subs)} submissions, want 2",
            )
            reasons.add(subs[0]["termination_reason"])
        _expect(
            report,
            "interrupted_by_no_capacity" in reasons,
            f"first-incarnation reasons {reasons} lack interrupted_by_no_capacity",
        )
        report["details"]["injected"] = engine.injected
        report["details"]["first_reasons"] = sorted(r for r in reasons if r)
    finally:
        await engine.stop()
        await app.shutdown()


_DRAIN_TRAIN = """
import os, sys, time
vol = sys.argv[1]
import jax
jax.config.update("jax_platforms", "cpu")
# Synchronous dispatch: these sim trainers churn buffers (resize /
# drain-restore) while the host is oversubscribed by the whole drill
# fleet; CPU async dispatch can still touch freed buffers from its
# dispatch thread (observed SIGSEGV / malloc corruption under load).
jax.config.update("jax_cpu_enable_async_dispatch", False)
try:
    import jax.extend.backend as _jb
    _jb.clear_backends()
except Exception:
    pass
from dstack_tpu.workloads.config import PRESETS
from dstack_tpu.workloads.train import (
    init_train_state, make_train_step, synthetic_batch, install_drain_handler,
)
from dstack_tpu.workloads import checkpoint as ckpt

drain = install_drain_handler()
cfg = PRESETS["tiny"]
state = init_train_state(cfg, jax.random.PRNGKey(0))
restored = ckpt.restore_latest(vol + "/ckpts", state)
start = 0
if restored is not None:
    state = restored
    start = int(state.step)
step = make_train_step(cfg)
batch = synthetic_batch(cfg, 2, 32)
for _ in range(start, 6):
    state, m = step(state, batch)
    with open(vol + "/progress", "w") as f:
        f.write(str(int(state.step)))
    if drain.draining:
        drain.checkpoint_and_exit(vol + "/ckpts", state)
    time.sleep(0.5)
    if drain.draining:
        drain.checkpoint_and_exit(vol + "/ckpts", state)
with open(vol + "/final", "w") as f:
    f.write(f"resumed_from={start} final={int(state.step)}")
"""


@scenario("preempt-resume")
async def _preempt_resume(report, seed, tmp: Path) -> None:
    """The flagship drill: a maintenance notice preempts ONE worker of a
    2-worker gang mid-training. The agent drains the job (SIGTERM), the
    workload checkpoints and exits DRAIN_EXIT_CODE, the server resubmits the
    gang exactly once, the retry resumes at step > 0, and /metrics reports
    1 preemption + 1 restart + 1 clean drain."""
    from dstack_tpu.server import settings

    settings.RETRY_PENDING_RUN_DELAY = 0
    script = tmp / "train.py"
    await asyncio.to_thread(script.write_text, _DRAIN_TRAIN)
    mount = tmp / "mnt" / "ckpt"
    engine = chaos.install(
        ChaosEngine(
            [
                {
                    "hook": "tick",
                    "action": "preempt",
                    "worker": 0,
                    "when_path_exists": str(mount / "progress"),
                    "message": "chaos: host maintenance",
                }
            ],
            seed=seed,
            name="preempt-resume",
        )
    )
    app, ctx, client = await _make_server(tpu_sim=["v5p-16"])
    try:
        await engine.start()
        resp = await client.post(
            "/api/project/main/volumes/create",
            json_body={"configuration": {
                "type": "volume", "name": "chaos-ckpt", "backend": "local",
                "region": "local", "size": "1GB",
            }},
        )
        _expect(report, resp.status == 200, f"volume create failed: {resp.body!r}")
        # Rank 0 execs the trainer so SIGTERM + the drain exit code reach the
        # runner unwrapped by bash; rank 1 waits for the final marker.
        rank0 = (
            f"PYTHONPATH={REPO_ROOT}:$PYTHONPATH exec python {script} {mount}"
        )
        rank1 = (
            f"while [ ! -f {mount}/final ]; do sleep 0.2; done; echo rank1 done"
        )
        cmd = f'if [ "$JAX_PROCESS_ID" = "0" ]; then {rank0}; else {rank1}; fi'
        body = _task_body(
            [cmd],
            "chaos-drill",
            resources={"tpu": "v5p-16"},
            retry={"on_events": ["interruption"], "duration": 600},
        )
        body["run_spec"]["configuration"]["volumes"] = [
            {"name": "chaos-ckpt", "path": str(mount)}
        ]
        resp = await client.post("/api/project/main/runs/submit", json_body=body)
        _expect(report, resp.status == 200, f"submit failed: {resp.body!r}")
        run = await _wait_run(client, "chaos-drill", {"done", "failed", "terminated"}, 180)
        _expect(report, run["status"] == "done", f"run ended {run['status']}, want done")

        reasons = set()
        for job in run["jobs"]:
            subs = job["job_submissions"]
            _expect(
                report,
                len(subs) == 2,
                f"job {job['job_spec']['job_num']}: {len(subs)} submissions,"
                " want 2 (gang resubmitted exactly once)",
            )
            reasons.add(subs[0]["termination_reason"])
        _expect(
            report,
            "preempted_by_provider" in reasons,
            f"first-incarnation reasons {reasons} lack preempted_by_provider",
        )

        final_path = mount / "final"
        resumed = -1
        if final_path.exists():
            final = await asyncio.to_thread(final_path.read_text)
            resumed = int(final.split("resumed_from=")[1].split()[0])
            report["details"]["final"] = final.strip()
        _expect(
            report,
            resumed > 0,
            f"resumed step {resumed}, want > 0 (checkpoint-resumed, not from scratch)",
        )

        resp = await client.get("/metrics", token="")
        text = resp.body.decode()
        for metric, want in [
            ("dstack_tpu_run_preemptions_total", 1),
            ("dstack_tpu_run_restarts_total", 1),
            ("dstack_tpu_run_clean_drains_total", 1),
        ]:
            line = next(
                (
                    ln
                    for ln in text.splitlines()
                    if ln.startswith(metric + "{") and 'run="chaos-drill"' in ln
                ),
                None,
            )
            val = float(line.rsplit(" ", 1)[1]) if line else None
            _expect(report, val == want, f"/metrics {metric} = {val}, want {want}")
        stage_buckets = [
            ln for ln in text.splitlines()
            if ln.startswith("dstack_tpu_run_stage_seconds_bucket{") and 'stage="' in ln
        ]
        _expect(
            report,
            bool(stage_buckets),
            "/metrics lacks dstack_tpu_run_stage_seconds_bucket series",
        )

        # The victim's persisted timeline must tell the preemption story in
        # order: notice (runner), graceful drain (runner), resubmit (FSM).
        from dstack_tpu.server.http import response_json

        resp = await client.get("/api/project/main/runs/chaos-drill/timeline")
        _expect(report, resp.status == 200, f"timeline fetch failed: {resp.body!r}")
        timeline = response_json(resp) or {"events": []}
        stages = [e["stage"] for e in timeline["events"]]
        report["details"]["timeline_stages"] = stages
        order = [stages.index(s) if s in stages else -1
                 for s in ("preempt", "drain", "resume")]
        _expect(
            report,
            -1 not in order and order[0] < order[1] < order[2],
            f"timeline stages {stages} lack ordered preempt -> drain -> resume",
        )
        _expect(
            report,
            timeline.get("trace_context") is None
            or timeline["trace_context"].startswith("00-"),
            f"timeline trace_context malformed: {timeline.get('trace_context')!r}",
        )
        report["details"]["injected"] = engine.injected
        report["details"]["first_reasons"] = sorted(r for r in reasons if r)
    finally:
        await engine.stop()
        await app.shutdown()


_VICTIM_TRAIN = """
import os, sys, time
vol = sys.argv[1]
import jax
jax.config.update("jax_platforms", "cpu")
# Synchronous dispatch: these sim trainers churn buffers (resize /
# drain-restore) while the host is oversubscribed by the whole drill
# fleet; CPU async dispatch can still touch freed buffers from its
# dispatch thread (observed SIGSEGV / malloc corruption under load).
jax.config.update("jax_cpu_enable_async_dispatch", False)
try:
    import jax.extend.backend as _jb
    _jb.clear_backends()
except Exception:
    pass
from dstack_tpu.workloads.config import PRESETS
from dstack_tpu.workloads.train import (
    init_train_state, make_train_step, synthetic_batch, install_drain_handler,
)
from dstack_tpu.workloads import checkpoint as ckpt

drain = install_drain_handler()
cfg = PRESETS["tiny"]
state = init_train_state(cfg, jax.random.PRNGKey(0))
restored = ckpt.restore_latest(vol + "/ckpts", state)
start = 0
if restored is not None:
    state = restored
    start = int(state.step)
step = make_train_step(cfg)
batch = synthetic_batch(cfg, 2, 32)
for _ in range(start, 400):
    state, m = step(state, batch)
    with open(vol + "/progress", "w") as f:
        f.write(str(int(state.step)))
    if drain.draining:
        drain.checkpoint_and_exit(vol + "/ckpts", state, grace_seconds=30.0)
    if os.path.exists(vol + "/stop"):
        break
    time.sleep(0.3)
    if drain.draining:
        drain.checkpoint_and_exit(vol + "/ckpts", state, grace_seconds=30.0)
with open(vol + "/final", "w") as f:
    f.write(f"resumed_from={start} final={int(state.step)}")
"""


@scenario("priority-preempt")
async def _priority_preempt(report, seed, tmp: Path) -> None:
    """Cluster-level priority preemption: the local fleet holds exactly ONE
    TPU slice (max_slices=1) and a priority-0 training run occupies it. A
    priority-50 run arrives, cannot place, and the scheduler reclaims
    capacity: the victim is cleanly drained (checkpoint + DRAIN_EXIT_CODE,
    reason preempted_by_scheduler), the high-priority run places on the
    freed slice and finishes, and the victim resumes from its drain
    checkpoint once capacity frees again. No chaos engine — the only
    "fault" is the scheduler doing its job."""
    from dstack_tpu.server import settings

    settings.RETRY_PENDING_RUN_DELAY = 0
    script = tmp / "train.py"
    await asyncio.to_thread(script.write_text, _VICTIM_TRAIN)
    mount = tmp / "mnt" / "ckpt"
    app, ctx, client = await _make_server(tpu_sim=["v5litepod-4"], max_slices=1)
    try:
        resp = await client.post(
            "/api/project/main/volumes/create",
            json_body={"configuration": {
                "type": "volume", "name": "chaos-ckpt", "backend": "local",
                "region": "local", "size": "1GB",
            }},
        )
        _expect(report, resp.status == 200, f"volume create failed: {resp.body!r}")
        body = _task_body(
            [f"PYTHONPATH={REPO_ROOT}:$PYTHONPATH exec python {script} {mount}"],
            "chaos-victim",
            resources={"tpu": "v5litepod-4"},
            retry={"on_events": ["interruption"], "duration": 600},
        )
        body["run_spec"]["configuration"]["volumes"] = [
            {"name": "chaos-ckpt", "path": str(mount)}
        ]
        resp = await client.post("/api/project/main/runs/submit", json_body=body)
        _expect(report, resp.status == 200, f"victim submit failed: {resp.body!r}")
        # The victim must be mid-training (checkpointable) before the
        # high-priority run shows up.
        progress = mount / "progress"
        for _ in range(600):
            if progress.exists():
                break
            await asyncio.sleep(0.2)
        _expect(report, progress.exists(), "victim never made training progress")

        body = _task_body(
            ["echo high-priority work done"],
            "chaos-highpri",
            resources={"tpu": "v5litepod-4"},
            priority=50,
        )
        resp = await client.post("/api/project/main/runs/submit", json_body=body)
        _expect(report, resp.status == 200, f"high-pri submit failed: {resp.body!r}")
        run = await _wait_run(
            client, "chaos-highpri", {"done", "failed", "terminated"}, 120
        )
        _expect(
            report, run["status"] == "done",
            f"high-pri run ended {run['status']}, want done (preemption placed it)",
        )

        # Let the resumed victim finish.
        await asyncio.to_thread((mount / "stop").write_text, "done")
        victim = await _wait_run(
            client, "chaos-victim", {"done", "failed", "terminated"}, 120
        )
        _expect(
            report, victim["status"] == "done",
            f"victim ended {victim['status']}, want done (resumed after preemption)",
        )
        subs = victim["jobs"][0]["job_submissions"]
        _expect(
            report, len(subs) == 2,
            f"victim has {len(subs)} submissions, want 2 (drained exactly once)",
        )
        _expect(
            report,
            subs[0]["termination_reason"] == "preempted_by_scheduler",
            f"victim first incarnation ended {subs[0]['termination_reason']},"
            " want preempted_by_scheduler",
        )
        final_path = mount / "final"
        resumed = -1
        if final_path.exists():
            final = await asyncio.to_thread(final_path.read_text)
            resumed = int(final.split("resumed_from=")[1].split()[0])
            report["details"]["final"] = final.strip()
        _expect(
            report, resumed > 0,
            f"victim resumed at step {resumed}, want > 0 (from the drain checkpoint)",
        )

        resp = await client.get("/metrics", token="")
        text = resp.body.decode()
        for metric, want in [
            ("dstack_tpu_run_scheduler_preemptions_total", 1),
            ("dstack_tpu_run_clean_drains_total", 1),
            ("dstack_tpu_run_restarts_total", 1),
            ("dstack_tpu_run_steps_lost_total", 0),
        ]:
            line = next(
                (
                    ln
                    for ln in text.splitlines()
                    if ln.startswith(metric + "{") and 'run="chaos-victim"' in ln
                ),
                None,
            )
            val = float(line.rsplit(" ", 1)[1]) if line else None
            _expect(report, val == want, f"/metrics {metric} = {val}, want {want}")
    finally:
        await app.shutdown()


_ELASTIC_TRAIN = """
import json, os, sys, time
vol = sys.argv[1]
os.environ["XLA_FLAGS"] = (
    "--xla_force_host_platform_device_count=4 " + os.environ.get("XLA_FLAGS", "")
)
import jax
jax.config.update("jax_platforms", "cpu")
# Synchronous dispatch: these sim trainers churn buffers (resize /
# drain-restore) while the host is oversubscribed by the whole drill
# fleet; CPU async dispatch can still touch freed buffers from its
# dispatch thread (observed SIGSEGV / malloc corruption under load).
jax.config.update("jax_cpu_enable_async_dispatch", False)
try:
    import jax.extend.backend as _jb
    _jb.clear_backends()
except Exception:
    pass
from dstack_tpu.parallel.mesh import rescale_accum_steps
from dstack_tpu.workloads.config import PRESETS
from dstack_tpu.workloads.sharding import make_mesh
from dstack_tpu.workloads.train import (
    init_train_state, make_train_step, read_resize_notice, synthetic_batch,
)
from dstack_tpu.workloads import checkpoint as ckpt

GLOBAL_BATCH = 12
cfg = PRESETS["tiny"]
devices = jax.devices()


_built = {}


def build(width, accum):
    # Cache per-width artifacts: re-expanding to a width already seen reuses
    # the mesh and compiled step (no recompile on rejoin).
    if (width, accum) not in _built:
        mesh = make_mesh(devices[:width], data=width)
        step = make_train_step(cfg, mesh, accum_steps=accum)
        batch = synthetic_batch(cfg, GLOBAL_BATCH, 32, mesh=mesh)
        _built[(width, accum)] = (mesh, step, batch)
    return _built[(width, accum)]


width, accum = 4, 3
mesh, step_fn, batch = build(width, accum)
state = init_train_state(cfg, jax.random.PRNGKey(0), mesh)
widths = [width]
steps_since_full = 0
for _ in range(200):
    notice = read_resize_notice()
    if notice and notice["width"] != width:
        # Shrink or re-expand: checkpoint, re-form the mesh at the new dp
        # width, reshard the state back in, rescale grad accumulation so
        # accum * width (the global batch) is invariant.
        ckpt.save(vol + "/ckpts", state, wait=True)
        ckpt.close_all()
        accum = rescale_accum_steps(accum, width, notice["width"])
        width = notice["width"]
        widths.append(width)
        mesh, step_fn, batch = build(width, accum)
        template = init_train_state(cfg, jax.random.PRNGKey(0), mesh)
        state = ckpt.restore_latest(vol + "/ckpts", template)
        steps_since_full = 0
    state, m = step_fn(state, batch)
    with open(vol + "/progress", "w") as f:
        f.write(str(int(state.step)))
    if width == 4 and len(widths) >= 3:
        steps_since_full += 1
        if steps_since_full >= 2:
            break
    time.sleep(0.3)
with open(vol + "/final", "w") as f:
    f.write(json.dumps({"widths": widths, "final_step": int(state.step)}))
"""


@scenario("elastic-resize")
async def _elastic_resize(report, seed, tmp: Path) -> None:
    """Elastic data-parallel recovery: a 4-host v5p-32 gang trains with
    elastic: true; chaos preempts worker 1 mid-run. Instead of restarting
    the gang, the server keeps the drained host's instance, notifies the
    survivors to re-form at width 3 (the rank-0 trainer reshards from its
    drain checkpoint and rescales grad accumulation to preserve the global
    batch), resubmits the lost rank in place, and re-expands to width 4
    when it rejoins. Rank 0 never restarts; no steps are lost."""
    from dstack_tpu.server import settings

    settings.RETRY_PENDING_RUN_DELAY = 0
    script = tmp / "train.py"
    await asyncio.to_thread(script.write_text, _ELASTIC_TRAIN)
    mount = tmp / "mnt" / "ckpt"
    engine = chaos.install(
        ChaosEngine(
            [
                {
                    "hook": "tick",
                    "action": "preempt",
                    "worker": 1,
                    "when_path_exists": str(mount / "progress"),
                    "message": "chaos: host maintenance",
                }
            ],
            seed=seed,
            name="elastic-resize",
        )
    )
    app, ctx, client = await _make_server(tpu_sim=["v5p-32"])
    try:
        await engine.start()
        resp = await client.post(
            "/api/project/main/volumes/create",
            json_body={"configuration": {
                "type": "volume", "name": "chaos-ckpt", "backend": "local",
                "region": "local", "size": "1GB",
            }},
        )
        _expect(report, resp.status == 200, f"volume create failed: {resp.body!r}")
        # Rank 0 execs the elastic trainer; other ranks model checkpointing
        # workers: exit DRAIN_EXIT_CODE on SIGTERM (a clean drain), park
        # until the trainer finishes otherwise.
        rank0 = f"PYTHONPATH={REPO_ROOT}:$PYTHONPATH exec python {script} {mount}"
        workers = (
            f"trap 'exit 113' TERM;"
            f" while [ ! -f {mount}/final ]; do sleep 0.2; done; echo rank done"
        )
        cmd = f'if [ "$JAX_PROCESS_ID" = "0" ]; then {rank0}; else {workers}; fi'
        body = _task_body(
            [cmd],
            "chaos-elastic",
            resources={"tpu": "v5p-32"},
            retry={"on_events": ["interruption"], "duration": 600},
            elastic=True,
        )
        body["run_spec"]["configuration"]["volumes"] = [
            {"name": "chaos-ckpt", "path": str(mount)}
        ]
        resp = await client.post("/api/project/main/runs/submit", json_body=body)
        _expect(report, resp.status == 200, f"submit failed: {resp.body!r}")
        run = await _wait_run(
            client, "chaos-elastic", {"done", "failed", "terminated"}, 240
        )
        _expect(report, run["status"] == "done", f"run ended {run['status']}, want done")
        _expect(report, engine.injected != [], "preempt event never fired")

        report["details"]["submissions"] = [
            {
                "job_num": job["job_spec"]["job_num"],
                "subs": [
                    {
                        "status": s["status"],
                        "reason": s.get("termination_reason"),
                        "exit": s.get("exit_status"),
                        "msg": s.get("termination_reason_message"),
                    }
                    for s in job["job_submissions"]
                ],
            }
            for job in run["jobs"]
        ]
        # Rank 0 must have survived on its FIRST submission — the whole
        # point of elastic mode is no full-gang restart.
        for job in run["jobs"]:
            subs = job["job_submissions"]
            num = job["job_spec"]["job_num"]
            want = 2 if num == 1 else 1
            _expect(
                report, len(subs) == want,
                f"job {num}: {len(subs)} submissions, want {want}",
            )

        final_path = mount / "final"
        widths = []
        if final_path.exists():
            import json as _json

            final = _json.loads(await asyncio.to_thread(final_path.read_text))
            widths = final["widths"]
            report["details"]["final"] = final
        _expect(
            report, widths == [4, 3, 4],
            f"trainer width history {widths}, want [4, 3, 4]"
            " (shrink on preemption, re-expand on rejoin)",
        )

        resp = await client.get("/metrics", token="")
        text = resp.body.decode()
        for metric, want in [
            ("dstack_tpu_run_elastic_resizes_total", 1),
            ("dstack_tpu_run_steps_lost_total", 0),
            ("dstack_tpu_run_restarts_total", 0),
        ]:
            line = next(
                (
                    ln
                    for ln in text.splitlines()
                    if ln.startswith(metric + "{") and 'run="chaos-elastic"' in ln
                ),
                None,
            )
            val = float(line.rsplit(" ", 1)[1]) if line else None
            _expect(report, val == want, f"/metrics {metric} = {val}, want {want}")
        report["details"]["injected"] = engine.injected
    finally:
        await engine.stop()
        await app.shutdown()


# ---- PR 9: failure-isolated serving tier drills ----------------------------
#
# Three drills proving the multi-replica control plane and the standalone
# data-plane workers fail independently: (a) kill -9 a server replica and
# watch the survivor take over its expired leases with zero double-claims;
# (b) kill -9 a data-plane worker mid-SSE and verify the other worker's
# streams are byte-intact while the killed streams end promptly; (c) cut
# the data plane off from the control-plane DB and verify it serves
# last-known routes flagged stale, then re-syncs epochs within one poll
# interval of recovery.


async def _seed_service_rows(ctx, run_name: str, port: int) -> str:
    """Insert a RUNNING service run + replica job pointing at
    127.0.0.1:port (same row shapes bench_proxy.py seeds). Returns run_id."""
    import json

    from dstack_tpu.models.runs import JobProvisioningData, JobSpec, RunSpec
    from dstack_tpu.server.security import generate_id
    from dstack_tpu.utils.common import utcnow_iso

    project = await ctx.db.fetchone("SELECT * FROM projects WHERE name='main'")
    user = await ctx.db.fetchone("SELECT * FROM users LIMIT 1")
    run_id, now = generate_id(), utcnow_iso()
    spec = RunSpec.model_validate(
        {"run_name": run_name, "repo_id": "local",
         "configuration": {"type": "service", "name": run_name, "port": port,
                           "commands": ["serve"]}}
    )
    await ctx.db.execute(
        "INSERT INTO runs (id, project_id, user_id, run_name, submitted_at,"
        " last_processed_at, status, run_spec, service_spec)"
        " VALUES (?, ?, ?, ?, ?, ?, 'running', ?, ?)",
        (run_id, project["id"], user["id"], run_name, now, now,
         spec.model_dump_json(),
         json.dumps({"url": f"/proxy/services/main/{run_name}/", "model": None})),
    )
    await ctx.db.execute(
        "INSERT INTO jobs (id, project_id, run_id, run_name, job_num, replica_num,"
        " submitted_at, last_processed_at, status, job_spec, job_provisioning_data)"
        " VALUES (?, ?, ?, ?, 0, 0, ?, ?, 'running', ?, ?)",
        (generate_id(), project["id"], run_id, run_name, now, now,
         _service_job_spec(run_name, port), _service_jpd()),
    )
    return run_id


def _service_job_spec(run_name: str, port: int) -> str:
    from dstack_tpu.models.runs import JobSpec

    return JobSpec.model_validate(
        {"job_name": f"{run_name}-0-0", "commands": ["serve"],
         "requirements": {"resources": {}},
         "app_specs": [{"app_name": "app", "port": port}]}
    ).model_dump_json()


def _service_jpd() -> str:
    from dstack_tpu.models.runs import JobProvisioningData

    return JobProvisioningData.model_validate(
        {"backend": "local",
         "instance_type": {"name": "local",
                           "resources": {"cpus": 1, "memory_mib": 1024}},
         "instance_id": "i-0", "hostname": "127.0.0.1", "internal_ip": "127.0.0.1",
         "region": "local", "price": 0.0, "username": "root", "dockerized": False}
    ).model_dump_json()


_REPLICA_WORKER = """
import asyncio, json, sys, time

from dstack_tpu.server.app import create_app
from dstack_tpu.server.http import Server


async def main():
    db_path, mode, keys_csv = sys.argv[1:4]
    keys = keys_csv.split(",")
    app = create_app(db_path=db_path, admin_token="chaos-admin",
                     run_background_tasks=True)
    server = Server(app, "127.0.0.1", 0)
    await server.start()
    ctx = app.state["ctx"]
    print(json.dumps({"event": "up", "port": server.port,
                      "replica": ctx.replica_id}), flush=True)
    if mode == "holder":
        held = []
        for k in keys:
            if await ctx.claims.try_claim("jobs", k):
                held.append(k)
                await ctx.db.execute(
                    "INSERT INTO chaos_claims (key, owner, acquired_at)"
                    " VALUES (?, ?, ?)", (k, ctx.replica_id, time.time()),
                )
        print(json.dumps({"event": "held", "keys": held}), flush=True)
        await asyncio.sleep(300)  # killed from outside; heartbeat renews
    else:  # contender: spin until every key is stolen from the corpse
        acquired = []
        while len(acquired) < len(keys):
            for k in keys:
                if k not in acquired and await ctx.claims.try_claim("jobs", k):
                    acquired.append(k)
                    await ctx.db.execute(
                        "INSERT INTO chaos_claims (key, owner, acquired_at)"
                        " VALUES (?, ?, ?)", (k, ctx.replica_id, time.time()),
                    )
            await asyncio.sleep(0.1)
        print(json.dumps({"event": "acquired", "keys": sorted(acquired)}),
              flush=True)
        await asyncio.sleep(300)  # parent scrapes /metrics, then kills us


asyncio.run(main())
"""


async def _read_event(proc, want: str, timeout: float = 60.0):
    """Next {"event": want} JSON line from a worker's stdout."""
    import json

    while True:
        line = await asyncio.wait_for(proc.stdout.readline(), timeout)
        if not line:
            raise RuntimeError(f"worker exited before event {want!r}")
        try:
            msg = json.loads(line)
        except ValueError:
            continue  # log noise on stdout
        if msg.get("event") == want:
            return msg


def _drill_env(tmp: Path, **extra: str) -> Dict[str, str]:
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO_ROOT,
        # Keep subprocess servers away from the operator's real config.
        "DSTACK_TPU_SERVER_CONFIG": str(tmp / "config.yml"),
    }
    env.update(extra)
    return env


@scenario("replica-kill-takeover")
async def _replica_kill_takeover(report, seed, tmp: Path) -> None:
    """kill -9 one of two server replicas mid-claim: the survivor must
    take over the corpse's leases within TTL, with zero double-claims
    (no acquisition before the dead replica's lease expiry), and the
    takeover must be visible on the survivor's /metrics."""
    import json as _json
    import sys
    import time

    import httpx

    ttl = 2.0
    keys = [f"drill-job-{i}" for i in range(4)]
    db = tmp / "replicas.db"

    # Parent-side control app: migrates the DB, creates the audit table,
    # and is our read handle on resource_leases / chaos_claims.
    from dstack_tpu.server.app import create_app

    app = create_app(db_path=str(db), admin_token="chaos-admin",
                     run_background_tasks=False)
    await app.startup()
    ctx = app.state["ctx"]
    await ctx.db.execute(
        "CREATE TABLE IF NOT EXISTS chaos_claims ("
        " key TEXT NOT NULL, owner TEXT NOT NULL, acquired_at REAL NOT NULL)"
    )

    script = tmp / "replica_worker.py"
    await asyncio.to_thread(script.write_text, _REPLICA_WORKER)

    def _spawn(replica_id: str, mode: str):
        # stderr to a file, not a pipe: nobody drains it, and a chatty FSM
        # filling the pipe buffer would deadlock the worker.
        errlog = open(tmp / f"{replica_id}.stderr", "wb")
        return asyncio.create_subprocess_exec(
            sys.executable, str(script), str(db), mode, ",".join(keys),
            stdout=asyncio.subprocess.PIPE, stderr=errlog,
            env=_drill_env(
                tmp,
                DSTACK_TPU_MULTI_REPLICA="1",
                DSTACK_TPU_REPLICA_ID=replica_id,
                DSTACK_TPU_LEASE_TTL=str(ttl),
            ),
        )

    proc_a = await _spawn("replica-a", "holder")
    proc_b = None
    try:
        held = await _read_event(proc_a, "held")
        _expect(report, sorted(held["keys"]) == sorted(keys),
                f"holder claimed {held['keys']}, want all of {keys}")

        proc_b = await _spawn("replica-b", "contender")
        up_b = await _read_event(proc_b, "up")
        b_port = up_b["port"]

        # Readiness gate: the contender's HTTP plane answers.
        async with httpx.AsyncClient(timeout=5) as hc:
            deadline = time.monotonic() + 15
            while True:
                try:
                    r = await hc.get(f"http://127.0.0.1:{b_port}/metrics")
                    if r.status_code == 200:
                        break
                except httpx.HTTPError:
                    pass
                _expect(report, time.monotonic() < deadline,
                        "contender /metrics never came up")
                if time.monotonic() >= deadline:
                    return
                await asyncio.sleep(0.1)

        # Let the contender demonstrably contend (and fail) while the
        # holder is alive, then snapshot the holder's lease expiries and
        # kill it without ceremony.
        await asyncio.sleep(2 * ttl / 4)
        pre_kill = await ctx.db.fetchall(
            "SELECT key, expires_at FROM resource_leases"
            " WHERE owner = 'replica-a' AND namespace = 'jobs'"
        )
        _expect(report, len(pre_kill) == len(keys),
                f"holder had {len(pre_kill)} leases at kill time, want {len(keys)}")
        expiry = {r["key"]: r["expires_at"] for r in pre_kill}
        stolen_early = await ctx.db.fetchall(
            "SELECT * FROM chaos_claims WHERE owner = 'replica-b'"
        )
        _expect(report, not stolen_early,
                "contender acquired keys while the holder was alive")
        t_kill = time.time()
        proc_a.kill()

        acquired = await _read_event(proc_b, "acquired",
                                     timeout=ttl + 20)
        _expect(report, acquired["keys"] == sorted(keys),
                f"contender acquired {acquired['keys']}, want {sorted(keys)}")
        rows = await ctx.db.fetchall(
            "SELECT key, acquired_at FROM chaos_claims WHERE owner = 'replica-b'"
        )
        takeover_at = {r["key"]: r["acquired_at"] for r in rows}
        double_claims = [
            k for k in keys
            if takeover_at.get(k, float("inf")) < expiry.get(k, 0) - 0.05
        ]
        _expect(report, not double_claims,
                f"double-claimed before lease expiry: {double_claims}")
        worst = max(takeover_at.values()) - t_kill if takeover_at else None
        _expect(report, worst is not None and worst <= ttl + 3,
                f"takeover took {worst}s after kill -9, want <= ttl+3")
        report["details"]["takeover_after_kill_s"] = round(worst, 3) if worst else None

        # The steal is observable: lease_takeovers ticked on the survivor.
        takeovers = 0.0
        async with httpx.AsyncClient(timeout=5) as hc:
            r = await hc.get(f"http://127.0.0.1:{b_port}/metrics")
            for ln in r.text.splitlines():
                if ln.startswith("dstack_tpu_lease_takeovers_total") and \
                        'namespace="jobs"' in ln:
                    takeovers = float(ln.rsplit(" ", 1)[1])
        _expect(report, takeovers >= 1,
                f"survivor /metrics lease_takeovers_total = {takeovers}, want >= 1")
        report["details"]["lease_takeovers_total"] = takeovers
    finally:
        for p in (proc_a, proc_b):
            if p is not None and p.returncode is None:
                p.kill()
                try:
                    await asyncio.wait_for(p.wait(), 10)
                except asyncio.TimeoutError:
                    pass
        await app.shutdown()


@scenario("dataplane-worker-kill")
async def _dataplane_worker_kill(report, seed, tmp: Path) -> None:
    """kill -9 one of two data-plane workers mid-SSE: the surviving
    worker's stream must arrive byte-intact, the killed worker's streams
    must end promptly (not hang), and the survivor stays ready."""
    import sys
    import time

    import httpx

    from dstack_tpu.server.app import create_app

    db = tmp / "dataplane.db"
    events = [f"event {i:03d}\n".encode() for i in range(30)]
    expected = b"".join(events)

    # Slow SSE-ish upstream: headers immediately, then one event every
    # 120 ms — long enough for a mid-stream kill, short enough for CI.
    async def _handle(reader, writer):
        try:
            await reader.readuntil(b"\r\n\r\n")
            writer.write(
                b"HTTP/1.1 200 OK\r\ncontent-type: text/event-stream\r\n"
                + b"content-length: %d\r\n\r\n" % len(expected)
            )
            await writer.drain()
            for e in events:
                writer.write(e)
                await writer.drain()
                await asyncio.sleep(0.12)
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            writer.close()

    upstream = await asyncio.start_server(_handle, "127.0.0.1", 0)
    uport = upstream.sockets[0].getsockname()[1]

    # Control plane: migrate + seed the service, then get out of the way
    # (the whole point is that workers need no live server process).
    app = create_app(db_path=str(db), admin_token="chaos-admin",
                     run_background_tasks=False)
    await app.startup()
    await _seed_service_rows(app.state["ctx"], "chaos-sse", uport)
    await app.shutdown()

    async def _spawn_worker(idx: int):
        errlog = await asyncio.to_thread(open, tmp / f"worker-{idx}.stderr", "wb")
        proc = await asyncio.create_subprocess_exec(
            sys.executable, "-m", "dstack_tpu.dataplane",
            "--db", str(db), "--port", "0", "--poll-interval", "0.2",
            stdout=asyncio.subprocess.PIPE, stderr=errlog,
            env=_drill_env(tmp),
        )
        line = await asyncio.wait_for(proc.stdout.readline(), 30)
        port = int(line.decode().rsplit(":", 1)[1])
        return proc, port

    async def _wait_ready(hc, port, deadline=15.0) -> bool:
        t0 = time.monotonic()
        while time.monotonic() - t0 < deadline:
            try:
                r = await hc.get(f"http://127.0.0.1:{port}/readyz")
                if r.status_code == 200:
                    return True
            except httpx.HTTPError:
                pass
            await asyncio.sleep(0.1)
        return False

    proc1 = proc2 = None
    hc = httpx.AsyncClient(timeout=httpx.Timeout(30, connect=5))
    try:
        (proc1, port1), (proc2, port2) = await asyncio.gather(
            _spawn_worker(1), _spawn_worker(2)
        )
        ready = await asyncio.gather(
            _wait_ready(hc, port1), _wait_ready(hc, port2)
        )
        _expect(report, all(ready), f"workers ready: {ready}, want both")
        if not all(ready):
            return

        progress = {1: 0, 2: 0}
        body: Dict[int, bytes] = {}
        errors: Dict[int, str] = {}

        async def _consume(idx: int, port: int) -> None:
            buf = b""
            try:
                async with hc.stream(
                    "GET",
                    f"http://127.0.0.1:{port}/proxy/services/main/chaos-sse/stream",
                    headers={"X-Request-ID": f"chaos-stream-{idx}"},
                ) as r:
                    async for chunk in r.aiter_raw():
                        buf += chunk
                        progress[idx] = len(buf)
            except Exception as e:  # the killed stream ends however it ends
                errors[idx] = repr(e)
            body[idx] = buf

        t1 = asyncio.create_task(_consume(1, port1))
        t2 = asyncio.create_task(_consume(2, port2))
        # Both streams demonstrably mid-flight (>= 5 events each), then
        # SIGKILL worker 1 — no shutdown hooks, no connection draining.
        five = 5 * len(events[0])
        deadline = time.monotonic() + 15
        while min(progress.values()) < five:
            _expect(report, time.monotonic() < deadline,
                    f"streams never reached mid-flight: {progress}")
            if time.monotonic() >= deadline:
                return
            await asyncio.sleep(0.05)
        t_kill = time.monotonic()
        proc1.kill()
        try:
            await asyncio.wait_for(t1, 10)
            killed_end = time.monotonic() - t_kill
        except asyncio.TimeoutError:
            t1.cancel()
            killed_end = None
        _expect(report, killed_end is not None,
                "killed worker's stream hung instead of ending")
        try:
            await asyncio.wait_for(t2, 30)
        except asyncio.TimeoutError:
            t2.cancel()
        _expect(report, body.get(2) == expected,
                f"surviving stream not byte-intact: got {len(body.get(2) or b'')}"
                f" bytes, want {len(expected)}")
        _expect(report, body.get(1) != expected,
                "killed stream implausibly completed after SIGKILL")
        r = await hc.get(f"http://127.0.0.1:{port2}/readyz")
        _expect(report, r.status_code == 200,
                f"survivor /readyz = {r.status_code} after the kill, want 200")
        # Trace continuity through the chaos: the survivor's flight
        # recorder must still serve its stream's trace after the sibling
        # died — observability that evaporates under failure is not
        # observability.
        tr = await hc.get(
            f"http://127.0.0.1:{port2}/v1/requests/chaos-stream-2/trace"
        )
        trace_ok = (
            tr.status_code == 200
            and tr.json().get("x_request_id") == "chaos-stream-2"
            and tr.json().get("status") == "ok"
            and [p["phase"] for p in tr.json().get("phases", [])] == ["proxy"]
        )
        _expect(report, trace_ok,
                f"survivor trace lookup failed: {tr.status_code}"
                f" {tr.text[:200]}")
        report["details"]["survivor_trace"] = (
            tr.json() if tr.status_code == 200 else None
        )
        report["details"]["killed_stream_ended_after_s"] = (
            round(killed_end, 3) if killed_end is not None else None
        )
        report["details"]["killed_stream_bytes"] = len(body.get(1) or b"")
        report["details"]["surviving_stream_bytes"] = len(body.get(2) or b"")
    finally:
        await hc.aclose()
        for p in (proc1, proc2):
            if p is not None and p.returncode is None:
                p.kill()
                try:
                    await asyncio.wait_for(p.wait(), 10)
                except asyncio.TimeoutError:
                    pass
        upstream.close()
        await upstream.wait_closed()


@scenario("dataplane-outage")
async def _dataplane_outage(report, seed, tmp: Path) -> None:
    """Control-plane outage: the data plane must keep serving last-known
    routes (flagged `x-dstack-route-stale`), stay ready, and re-sync
    epochs within ~one poll interval of the control plane returning —
    including a topology change that happened during the outage."""
    import time

    from dstack_tpu.dataplane.app import (
        create_dataplane_app, route_staleness_seconds,
    )
    from dstack_tpu.server.app import create_app
    from dstack_tpu.server.http import TestClient

    db = tmp / "outage.db"
    poll = 0.25

    async def _make_upstream(payload: bytes):
        async def _handle(reader, writer):
            try:
                while True:
                    await reader.readuntil(b"\r\n\r\n")
                    writer.write(
                        b"HTTP/1.1 200 OK\r\ncontent-length: %d\r\n\r\n"
                        % len(payload) + payload
                    )
                    await writer.drain()
            except (ConnectionError, asyncio.IncompleteReadError):
                pass
            finally:
                writer.close()

        srv = await asyncio.start_server(_handle, "127.0.0.1", 0)
        return srv, srv.sockets[0].getsockname()[1]

    up_a, port_a = await _make_upstream(b"alpha")
    up_b, port_b = await _make_upstream(b"bravo")

    app = create_app(db_path=str(db), admin_token="chaos-admin",
                     run_background_tasks=False)
    await app.startup()
    ctx = app.state["ctx"]
    run_id = await _seed_service_rows(ctx, "outage-svc", port_a)

    dp = create_dataplane_app(str(db), poll_interval=poll, routing_ttl=0.4)
    await dp.startup()
    dpc = dp.state["ctx"]
    client = TestClient(dp)

    async def _get(path):
        resp = await client.get(path)
        if resp.stream is not None:
            chunks = []
            async for c in resp.stream:
                chunks.append(c)
            resp.body = b"".join(chunks)
        return resp

    class _DeadDB:
        """Every query raises — the worker's view of a down control
        plane. Real db object kept so non-query attributes still work."""

        def __init__(self, real):
            self._real = real

        def __getattr__(self, name):
            if name in ("fetchone", "fetchall", "execute", "executemany",
                        "run_sync"):
                async def _fail(*a, **k):
                    raise RuntimeError("control plane unreachable (chaos)")
                return _fail
            return getattr(self._real, name)

    try:
        deadline = time.monotonic() + 15
        while not dpc.synced_once and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        _expect(report, dpc.synced_once, "worker never achieved epoch sync")
        r = await _get("/proxy/services/main/outage-svc/data")
        _expect(report, r.status == 200 and r.body == b"alpha",
                f"pre-outage request: {r.status} {r.body[:40]!r}")
        _expect(report, r.headers.get("x-dstack-route-stale") is None,
                "fresh route wrongly flagged stale")

        # --- outage: cut the worker off from the DB entirely.
        real_db = dpc.db
        dpc.db = _DeadDB(real_db)
        await asyncio.sleep(0.6)  # routing TTL expires; epoch polls fail
        r = await _get("/proxy/services/main/outage-svc/data")
        _expect(report, r.status == 200 and r.body == b"alpha",
                f"during outage: {r.status} {r.body[:40]!r}, want cached 200")
        _expect(report, r.headers.get("x-dstack-route-stale") == "1",
                "degraded-mode response missing x-dstack-route-stale: 1")
        ready = await _get("/readyz")
        _expect(report, ready.status == 200,
                f"/readyz {ready.status} during outage, want 200 (stays ready)")
        await asyncio.sleep(poll)
        staleness = route_staleness_seconds(dpc)
        _expect(report, staleness > 0,
                f"staleness gauge {staleness} during outage, want > 0")
        report["details"]["outage_staleness_s"] = round(staleness, 3)
        report["details"]["stale_serves"] = dpc.routing_cache.stats()["stale_serves"]

        # While the worker is cut off, the FSM moves the service to a new
        # replica (port flip + epoch bump) — exactly what the worker must
        # pick up on recovery.
        await ctx.db.execute(
            "UPDATE jobs SET job_spec = ? WHERE run_id = ?",
            (_service_job_spec("outage-svc", port_b), run_id),
        )
        await ctx.db.execute(
            "UPDATE runs SET routing_epoch = routing_epoch + 1 WHERE id = ?",
            (run_id,),
        )

        # --- recovery: reconnect and measure epoch re-sync latency.
        dpc.db = real_db
        t0 = time.monotonic()
        resynced = None
        while time.monotonic() - t0 < poll * 4 + 2:
            r = await _get("/proxy/services/main/outage-svc/data")
            if r.status == 200 and r.body == b"bravo":
                resynced = time.monotonic() - t0
                _expect(report, r.headers.get("x-dstack-route-stale") is None,
                        "post-recovery response still flagged stale")
                break
            await asyncio.sleep(0.05)
        _expect(report, resynced is not None,
                "worker never picked up the epoch bump after recovery")
        _expect(report, resynced is None or resynced <= poll + 1.0,
                f"epoch re-sync took {resynced}s, want <= poll + 1.0")
        if resynced is not None:
            report["details"]["resync_after_recovery_s"] = round(resynced, 3)
        await asyncio.sleep(poll + 0.1)
        _expect(report, route_staleness_seconds(dpc) < poll + 1.0,
                "staleness gauge did not recover after reconnection")
    finally:
        await dp.shutdown()
        await app.shutdown()
        for srv in (up_a, up_b):
            srv.close()
            await srv.wait_closed()


_SHARD_WORKER = """
import asyncio, json, sys, time

from dstack_tpu.server.app import create_app
from dstack_tpu.server.http import Server


async def main():
    db_path = sys.argv[1]
    app = create_app(db_path=db_path, admin_token="chaos-admin",
                     run_background_tasks=True)
    server = Server(app, "127.0.0.1", 0)
    await server.start()
    ctx = app.state["ctx"]
    print(json.dumps({"event": "up", "port": server.port,
                      "replica": ctx.replica_id}), flush=True)
    # Audit trail: every shard acquisition gets a wall-clock row. The
    # parent compares these against the victim's snapshotted lease
    # expiries to prove no survivor stole a shard early. Polling lags
    # the lease write by <= 50ms, which only makes the recorded time
    # LATER -- it can never fake a pre-expiry steal.
    owned = frozenset()
    while True:
        now_owned = ctx.shard_map.owned()
        for n in sorted(now_owned - owned):
            await ctx.db.execute(
                "INSERT INTO chaos_shards (shard, owner, acquired_at)"
                " VALUES (?, ?, ?)", (n, ctx.replica_id, time.time()),
            )
        owned = now_owned
        await asyncio.sleep(0.05)


asyncio.run(main())
"""


@scenario("shard-kill")
async def _shard_kill(report, seed, tmp: Path) -> None:
    """kill -9 one of four sharded replicas mid-probe: the survivors
    must absorb the corpse's FSM shards within one lease TTL of expiry,
    with zero pre-expiry steals (the lease boundary is the only handoff
    authority), and every in-flight run still reaches `done` -- the
    blast radius of a replica death is one TTL of latency on its
    shards, never a stuck run."""
    import json as _json
    import signal
    import sys
    import time

    import httpx

    from dstack_tpu.server.services.shard_map import NS_SHARD

    ttl = 2.0
    n_replicas = 4
    n_shards = 16
    n_runs = 12
    db = tmp / "shards.db"

    # Parent-side control app (not multi-replica, no background tasks):
    # migrates the DB, owns the audit table, reads leases and run rows.
    from dstack_tpu.server.app import create_app

    app = create_app(db_path=str(db), admin_token="chaos-admin",
                     run_background_tasks=False)
    await app.startup()
    ctx = app.state["ctx"]
    await ctx.db.execute(
        "CREATE TABLE IF NOT EXISTS chaos_shards ("
        " shard INTEGER NOT NULL, owner TEXT NOT NULL,"
        " acquired_at REAL NOT NULL)"
    )

    script = tmp / "shard_worker.py"
    await asyncio.to_thread(script.write_text, _SHARD_WORKER)

    def _spawn(replica_id: str):
        errlog = open(tmp / f"{replica_id}.stderr", "wb")
        return asyncio.create_subprocess_exec(
            sys.executable, str(script), str(db),
            stdout=asyncio.subprocess.PIPE, stderr=errlog,
            env=_drill_env(
                tmp,
                DSTACK_TPU_MULTI_REPLICA="1",
                DSTACK_TPU_REPLICA_ID=replica_id,
                DSTACK_TPU_LEASE_TTL=str(ttl),
                DSTACK_TPU_FSM_SHARDS=str(n_shards),
            ),
        )

    names = [f"replica-{i}" for i in range(n_replicas)]
    procs = {}
    try:
        for name in names:
            procs[name] = await _spawn(name)
        ports = {}
        for name in names:
            up = await _read_event(procs[name], "up")
            ports[name] = up["port"]

        async def _lease_map():
            now = time.time()
            rows = await ctx.db.fetchall(
                "SELECT key, owner, expires_at FROM resource_leases"
                " WHERE namespace = ? AND expires_at > ?", (NS_SHARD, now),
            )
            return {int(r["key"]): (r["owner"], r["expires_at"]) for r in rows}

        # Convergence gate: all shards leased, perfectly fair (4 each).
        deadline = time.monotonic() + 30
        while True:
            leases = await _lease_map()
            per_owner = {}
            for owner, _ in leases.values():
                per_owner[owner] = per_owner.get(owner, 0) + 1
            if len(leases) == n_shards and \
                    sorted(per_owner.values()) == [4] * n_replicas:
                break
            _expect(report, time.monotonic() < deadline,
                    f"shards never balanced: {per_owner}")
            if time.monotonic() >= deadline:
                return
            await asyncio.sleep(0.1)
        report["details"]["balanced_assignment"] = {
            o: n for o, n in sorted(per_owner.items())
        }

        # Mid-probe load: real runs through the sharded FSM, submitted
        # to replica-0's API (which stays alive).
        api = f"http://127.0.0.1:{ports['replica-0']}"
        hdrs = {"Authorization": "Bearer chaos-admin"}
        run_names = [f"shardkill-{i:02d}" for i in range(n_runs)]
        async with httpx.AsyncClient(timeout=30) as hc:
            for rn in run_names:
                r = await hc.post(f"{api}/api/project/main/runs/submit",
                                  headers=hdrs, json=_task_body(["true"], rn))
                _expect(report, r.status_code == 200,
                        f"submit {rn} -> {r.status_code}: {r.text[:200]}")

        # Snapshot the victim's lease expiries, then kill it mid-flight.
        victim = "replica-3"
        leases = await _lease_map()
        victim_shards = {n: exp for n, (o, exp) in leases.items() if o == victim}
        _expect(report, len(victim_shards) == 4,
                f"victim held {len(victim_shards)} shards at kill, want 4")
        t_kill = time.time()
        procs[victim].kill()

        # Survivors must own ALL shards again within one TTL of the
        # victim's last lease expiry (tick cadence is ttl/4; generous
        # slack for a 1-core box mid run-churn).
        reassigned_at = None
        deadline = time.monotonic() + 3 * ttl + 30
        while time.monotonic() < deadline:
            leases = await _lease_map()
            owners = {o for o, _ in leases.values()}
            if len(leases) == n_shards and victim not in owners:
                reassigned_at = time.time()
                break
            await asyncio.sleep(0.1)
        _expect(report, reassigned_at is not None,
                "survivors never absorbed the victim's shards")
        if reassigned_at is not None:
            report["details"]["reassigned_after_kill_s"] = round(
                reassigned_at - t_kill, 3)

        # Zero pre-expiry steals: every takeover row for a victim shard
        # is stamped at or after that shard's snapshotted lease expiry.
        rows = await ctx.db.fetchall(
            "SELECT shard, owner, acquired_at FROM chaos_shards"
            " WHERE acquired_at > ? AND owner != ?", (t_kill, victim),
        )
        early = [
            (r["shard"], r["owner"])
            for r in rows
            if r["shard"] in victim_shards
            and r["acquired_at"] < victim_shards[r["shard"]] - 0.05
        ]
        _expect(report, not early,
                f"shards stolen before the victim's lease expired: {early}")

        # The kill must not strand a single run: shards moved, rows kept
        # flowing (per-row claims stay the correctness backstop).
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            rows = await ctx.db.fetchall(
                "SELECT run_name, status FROM runs WHERE deleted = 0")
            status = {r["run_name"]: r["status"] for r in rows
                      if r["run_name"] in set(run_names)}
            if len(status) == n_runs and \
                    all(s in ("done", "failed", "terminated")
                        for s in status.values()):
                break
            await asyncio.sleep(0.5)
        not_done = {n: s for n, s in status.items() if s != "done"}
        missing = [n for n in run_names if n not in status]
        _expect(report, not not_done and not missing,
                f"runs not done after takeover: {not_done or missing}")
        report["details"]["runs_done"] = sum(
            1 for s in status.values() if s == "done")

        # Observability: the rebalance is visible on survivor /metrics --
        # the owned-shards gauges sum to the full shard space and at
        # least one survivor counted an `acquired` rebalance post-kill.
        owned_total, acquired_total = 0.0, 0.0
        async with httpx.AsyncClient(timeout=10) as hc:
            for name in names:
                if name == victim:
                    continue
                r = await hc.get(f"http://127.0.0.1:{ports[name]}/metrics")
                for ln in r.text.splitlines():
                    if ln.startswith("dstack_tpu_fsm_shards_owned"):
                        owned_total += float(ln.rsplit(" ", 1)[1])
                    if ln.startswith("dstack_tpu_fsm_shard_rebalances_total") \
                            and 'action="acquired"' in ln:
                        acquired_total += float(ln.rsplit(" ", 1)[1])
        _expect(report, owned_total == n_shards,
                f"survivor shards_owned gauges sum to {owned_total},"
                f" want {n_shards}")
        _expect(report, acquired_total >= n_shards,
                f"rebalance counters show {acquired_total} acquisitions,"
                f" want >= {n_shards}")
        report["details"]["survivor_shards_owned_sum"] = owned_total
    finally:
        for p in procs.values():
            if p is not None and p.returncode is None:
                p.kill()
                try:
                    await asyncio.wait_for(p.wait(), 10)
                except asyncio.TimeoutError:
                    pass
        await app.shutdown()
