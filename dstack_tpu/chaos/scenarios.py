"""Bundled chaos scenarios: an in-process server + local backend + the chaos
engine, with pass/fail expectations — the headless face of the subsystem
(`python -m dstack_tpu.chaos --scenario NAME`) and the fixture behind the
tier-1 chaos tests.

Each scenario boots a fresh in-memory server with background FSMs running,
installs a seeded `ChaosEngine`, submits a run on the local backend (real
runner subprocesses), and asserts the recovery story end to end. The report
is plain data so the CLI can render it and CI can gate on `ok`.
"""

import asyncio
import os
import tempfile
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional

from dstack_tpu import chaos
from dstack_tpu.chaos.engine import ChaosEngine

REPO_ROOT = str(Path(__file__).resolve().parent.parent.parent)

SCENARIOS: Dict[str, Callable] = {}


def scenario(name: str):
    def deco(fn):
        SCENARIOS[name] = fn
        return fn

    return deco


def list_scenarios() -> List[str]:
    return sorted(SCENARIOS)


async def run_scenario(name: str, seed: int = 0) -> Dict[str, Any]:
    """Run one scenario; returns {name, seed, ok, failures, details}."""
    if name not in SCENARIOS:
        raise ValueError(f"unknown scenario {name!r}; have {list_scenarios()}")
    from dstack_tpu.server import settings

    saved = {
        k: getattr(settings, k)
        for k in ("RETRY_PENDING_RUN_DELAY", "RUNNER_DISCONNECT_GRACE")
    }
    report: Dict[str, Any] = {"name": name, "seed": seed, "failures": [], "details": {}}
    try:
        with tempfile.TemporaryDirectory(prefix=f"dstack-chaos-{name}-") as tmp:
            await SCENARIOS[name](report, seed, Path(tmp))
    finally:
        for k, v in saved.items():
            setattr(settings, k, v)
        chaos.uninstall()
    report["ok"] = not report["failures"]
    return report


def _expect(report: Dict[str, Any], cond: bool, what: str) -> None:
    if not cond:
        report["failures"].append(what)


async def _make_server(
    tpu_sim: Optional[List[str]] = None, **backend_overrides
):
    from dstack_tpu.server.app import create_app
    from dstack_tpu.server.http import TestClient

    app = create_app(db_path=":memory:", run_background_tasks=True)
    await app.startup()
    ctx = app.state["ctx"]
    if tpu_sim or backend_overrides:
        conf = dict(backend_overrides)
        if tpu_sim:
            conf["tpu_sim"] = tpu_sim
        ctx.overrides["local_backend_config"] = conf
    client = TestClient(app, token=app.state["admin_token"])
    return app, ctx, client


async def _wait_run(client, run_name: str, targets, timeout: float):
    from dstack_tpu.server.http import response_json

    deadline = asyncio.get_event_loop().time() + timeout
    while True:
        resp = await client.post(
            "/api/project/main/runs/get", json_body={"run_name": run_name}
        )
        run = response_json(resp)
        if run and run.get("status") in targets:
            return run
        if asyncio.get_event_loop().time() > deadline:
            return run
        await asyncio.sleep(0.2)


def _task_body(commands, run_name, resources=None, retry=None, nodes=1, **conf_extra):
    conf: Dict[str, Any] = {
        "type": "task",
        "commands": commands,
        "nodes": nodes,
        "resources": resources or {"cpu": "1..", "memory": "0.1.."},
        **conf_extra,
    }
    if retry is not None:
        conf["retry"] = retry
    return {
        "run_spec": {
            "run_name": run_name,
            "configuration": conf,
            "ssh_key_pub": "ssh-rsa CHAOS",
        }
    }


# ---- scenarios -------------------------------------------------------------


@scenario("runner-flap")
async def _runner_flap(report, seed, tmp: Path) -> None:
    """Transient agent flakes: two consecutive /api/pull failures injected
    mid-run must be absorbed by the disconnect grace — the run finishes on
    its FIRST submission, no resubmit."""
    from dstack_tpu.server import settings

    settings.RETRY_PENDING_RUN_DELAY = 0
    engine = chaos.install(
        ChaosEngine(
            [
                {
                    "hook": "runner.http",
                    "action": "error",
                    "match": {"path": "/api/pull"},
                    "at_call": 2,
                    "calls": 2,
                    "message": "chaos: dropped heartbeat",
                }
            ],
            seed=seed,
            name="runner-flap",
        )
    )
    app, ctx, client = await _make_server()
    try:
        await engine.start()
        body = _task_body(
            ["sleep 2; echo flap-survived"],
            "chaos-flap",
            retry={"on_events": ["interruption"], "duration": 600},
        )
        resp = await client.post("/api/project/main/runs/submit", json_body=body)
        _expect(report, resp.status == 200, f"submit failed: {resp.body!r}")
        run = await _wait_run(client, "chaos-flap", {"done", "failed", "terminated"}, 60)
        _expect(report, run["status"] == "done", f"run ended {run['status']}, want done")
        subs = run["jobs"][0]["job_submissions"]
        _expect(
            report,
            len(subs) == 1,
            f"{len(subs)} submissions, want 1 (grace should absorb the flap)",
        )
        _expect(
            report,
            len(engine.injected) >= 2,
            f"engine injected {len(engine.injected)} faults, want >= 2",
        )
        report["details"]["injected"] = engine.injected
        report["details"]["submissions"] = len(subs)
    finally:
        await engine.stop()
        await app.shutdown()


@scenario("hard-preempt")
async def _hard_preempt(report, seed, tmp: Path) -> None:
    """A reclaimed VM with no notice: SIGKILL one worker's runner of a
    2-worker gang mid-run. The server must classify the dead agent as an
    interruption, kill the sibling, and resubmit the gang once."""
    from dstack_tpu.server import settings

    settings.RETRY_PENDING_RUN_DELAY = 0
    settings.RUNNER_DISCONNECT_GRACE = 1.0
    started = tmp / "started"
    crash_done = tmp / "crash-done"
    engine = chaos.install(
        ChaosEngine(
            [
                {
                    "hook": "tick",
                    "action": "crash",
                    "worker": 1,
                    "when_path_exists": str(started),
                    "message": "chaos: VM reclaimed",
                }
            ],
            seed=seed,
            name="hard-preempt",
        )
    )
    app, ctx, client = await _make_server(tpu_sim=["v5p-16"])
    try:
        await engine.start()
        # Both ranks check the crash marker ONCE at startup: the first
        # incarnation (marker absent) parks until the server tears it down
        # after the crash; the resubmitted gang (marker present — written
        # below once the injection is observed) finishes fast. Rank 0 also
        # opens the chaos window by touching the `started` gate.
        cmd = (
            f'[ "$JAX_PROCESS_ID" = "0" ] && touch {started};'
            f" if [ -f {crash_done} ]; then sleep 1; echo retried rank done;"
            f" else sleep 300; fi"
        )
        body = _task_body(
            [cmd],
            "chaos-hard",
            resources={"tpu": "v5p-16"},
            retry={"on_events": ["interruption"], "duration": 600},
        )
        resp = await client.post("/api/project/main/runs/submit", json_body=body)
        _expect(report, resp.status == 200, f"submit failed: {resp.body!r}")
        for _ in range(300):  # release the retried gang once the crash fired
            if engine.injected:
                await asyncio.to_thread(crash_done.write_text, "crashed")
                break
            await asyncio.sleep(0.2)
        _expect(report, engine.injected != [], "crash event never fired")
        run = await _wait_run(client, "chaos-hard", {"done", "failed", "terminated"}, 120)
        _expect(report, run["status"] == "done", f"run ended {run['status']}, want done")
        reasons = set()
        for job in run["jobs"]:
            subs = job["job_submissions"]
            _expect(
                report,
                len(subs) == 2,
                f"job {job['job_spec']['job_num']}: {len(subs)} submissions, want 2",
            )
            reasons.add(subs[0]["termination_reason"])
        _expect(
            report,
            "interrupted_by_no_capacity" in reasons,
            f"first-incarnation reasons {reasons} lack interrupted_by_no_capacity",
        )
        report["details"]["injected"] = engine.injected
        report["details"]["first_reasons"] = sorted(r for r in reasons if r)
    finally:
        await engine.stop()
        await app.shutdown()


_DRAIN_TRAIN = """
import os, sys, time
vol = sys.argv[1]
import jax
jax.config.update("jax_platforms", "cpu")
# Synchronous dispatch: these sim trainers churn buffers (resize /
# drain-restore) while the host is oversubscribed by the whole drill
# fleet; CPU async dispatch can still touch freed buffers from its
# dispatch thread (observed SIGSEGV / malloc corruption under load).
jax.config.update("jax_cpu_enable_async_dispatch", False)
try:
    import jax.extend.backend as _jb
    _jb.clear_backends()
except Exception:
    pass
from dstack_tpu.workloads.config import PRESETS
from dstack_tpu.workloads.train import (
    init_train_state, make_train_step, synthetic_batch, install_drain_handler,
)
from dstack_tpu.workloads import checkpoint as ckpt

drain = install_drain_handler()
cfg = PRESETS["tiny"]
state = init_train_state(cfg, jax.random.PRNGKey(0))
restored = ckpt.restore_latest(vol + "/ckpts", state)
start = 0
if restored is not None:
    state = restored
    start = int(state.step)
step = make_train_step(cfg)
batch = synthetic_batch(cfg, 2, 32)
for _ in range(start, 6):
    state, m = step(state, batch)
    with open(vol + "/progress", "w") as f:
        f.write(str(int(state.step)))
    if drain.draining:
        drain.checkpoint_and_exit(vol + "/ckpts", state)
    time.sleep(0.5)
    if drain.draining:
        drain.checkpoint_and_exit(vol + "/ckpts", state)
with open(vol + "/final", "w") as f:
    f.write(f"resumed_from={start} final={int(state.step)}")
"""


@scenario("preempt-resume")
async def _preempt_resume(report, seed, tmp: Path) -> None:
    """The flagship drill: a maintenance notice preempts ONE worker of a
    2-worker gang mid-training. The agent drains the job (SIGTERM), the
    workload checkpoints and exits DRAIN_EXIT_CODE, the server resubmits the
    gang exactly once, the retry resumes at step > 0, and /metrics reports
    1 preemption + 1 restart + 1 clean drain."""
    from dstack_tpu.server import settings

    settings.RETRY_PENDING_RUN_DELAY = 0
    script = tmp / "train.py"
    await asyncio.to_thread(script.write_text, _DRAIN_TRAIN)
    mount = tmp / "mnt" / "ckpt"
    engine = chaos.install(
        ChaosEngine(
            [
                {
                    "hook": "tick",
                    "action": "preempt",
                    "worker": 0,
                    "when_path_exists": str(mount / "progress"),
                    "message": "chaos: host maintenance",
                }
            ],
            seed=seed,
            name="preempt-resume",
        )
    )
    app, ctx, client = await _make_server(tpu_sim=["v5p-16"])
    try:
        await engine.start()
        resp = await client.post(
            "/api/project/main/volumes/create",
            json_body={"configuration": {
                "type": "volume", "name": "chaos-ckpt", "backend": "local",
                "region": "local", "size": "1GB",
            }},
        )
        _expect(report, resp.status == 200, f"volume create failed: {resp.body!r}")
        # Rank 0 execs the trainer so SIGTERM + the drain exit code reach the
        # runner unwrapped by bash; rank 1 waits for the final marker.
        rank0 = (
            f"PYTHONPATH={REPO_ROOT}:$PYTHONPATH exec python {script} {mount}"
        )
        rank1 = (
            f"while [ ! -f {mount}/final ]; do sleep 0.2; done; echo rank1 done"
        )
        cmd = f'if [ "$JAX_PROCESS_ID" = "0" ]; then {rank0}; else {rank1}; fi'
        body = _task_body(
            [cmd],
            "chaos-drill",
            resources={"tpu": "v5p-16"},
            retry={"on_events": ["interruption"], "duration": 600},
        )
        body["run_spec"]["configuration"]["volumes"] = [
            {"name": "chaos-ckpt", "path": str(mount)}
        ]
        resp = await client.post("/api/project/main/runs/submit", json_body=body)
        _expect(report, resp.status == 200, f"submit failed: {resp.body!r}")
        run = await _wait_run(client, "chaos-drill", {"done", "failed", "terminated"}, 180)
        _expect(report, run["status"] == "done", f"run ended {run['status']}, want done")

        reasons = set()
        for job in run["jobs"]:
            subs = job["job_submissions"]
            _expect(
                report,
                len(subs) == 2,
                f"job {job['job_spec']['job_num']}: {len(subs)} submissions,"
                " want 2 (gang resubmitted exactly once)",
            )
            reasons.add(subs[0]["termination_reason"])
        _expect(
            report,
            "preempted_by_provider" in reasons,
            f"first-incarnation reasons {reasons} lack preempted_by_provider",
        )

        final_path = mount / "final"
        resumed = -1
        if final_path.exists():
            final = await asyncio.to_thread(final_path.read_text)
            resumed = int(final.split("resumed_from=")[1].split()[0])
            report["details"]["final"] = final.strip()
        _expect(
            report,
            resumed > 0,
            f"resumed step {resumed}, want > 0 (checkpoint-resumed, not from scratch)",
        )

        resp = await client.get("/metrics", token="")
        text = resp.body.decode()
        for metric, want in [
            ("dstack_tpu_run_preemptions_total", 1),
            ("dstack_tpu_run_restarts_total", 1),
            ("dstack_tpu_run_clean_drains_total", 1),
        ]:
            line = next(
                (
                    ln
                    for ln in text.splitlines()
                    if ln.startswith(metric + "{") and 'run="chaos-drill"' in ln
                ),
                None,
            )
            val = float(line.rsplit(" ", 1)[1]) if line else None
            _expect(report, val == want, f"/metrics {metric} = {val}, want {want}")
        stage_buckets = [
            ln for ln in text.splitlines()
            if ln.startswith("dstack_tpu_run_stage_seconds_bucket{") and 'stage="' in ln
        ]
        _expect(
            report,
            bool(stage_buckets),
            "/metrics lacks dstack_tpu_run_stage_seconds_bucket series",
        )

        # The victim's persisted timeline must tell the preemption story in
        # order: notice (runner), graceful drain (runner), resubmit (FSM).
        from dstack_tpu.server.http import response_json

        resp = await client.get("/api/project/main/runs/chaos-drill/timeline")
        _expect(report, resp.status == 200, f"timeline fetch failed: {resp.body!r}")
        timeline = response_json(resp) or {"events": []}
        stages = [e["stage"] for e in timeline["events"]]
        report["details"]["timeline_stages"] = stages
        order = [stages.index(s) if s in stages else -1
                 for s in ("preempt", "drain", "resume")]
        _expect(
            report,
            -1 not in order and order[0] < order[1] < order[2],
            f"timeline stages {stages} lack ordered preempt -> drain -> resume",
        )
        _expect(
            report,
            timeline.get("trace_context") is None
            or timeline["trace_context"].startswith("00-"),
            f"timeline trace_context malformed: {timeline.get('trace_context')!r}",
        )
        report["details"]["injected"] = engine.injected
        report["details"]["first_reasons"] = sorted(r for r in reasons if r)
    finally:
        await engine.stop()
        await app.shutdown()


_VICTIM_TRAIN = """
import os, sys, time
vol = sys.argv[1]
import jax
jax.config.update("jax_platforms", "cpu")
# Synchronous dispatch: these sim trainers churn buffers (resize /
# drain-restore) while the host is oversubscribed by the whole drill
# fleet; CPU async dispatch can still touch freed buffers from its
# dispatch thread (observed SIGSEGV / malloc corruption under load).
jax.config.update("jax_cpu_enable_async_dispatch", False)
try:
    import jax.extend.backend as _jb
    _jb.clear_backends()
except Exception:
    pass
from dstack_tpu.workloads.config import PRESETS
from dstack_tpu.workloads.train import (
    init_train_state, make_train_step, synthetic_batch, install_drain_handler,
)
from dstack_tpu.workloads import checkpoint as ckpt

drain = install_drain_handler()
cfg = PRESETS["tiny"]
state = init_train_state(cfg, jax.random.PRNGKey(0))
restored = ckpt.restore_latest(vol + "/ckpts", state)
start = 0
if restored is not None:
    state = restored
    start = int(state.step)
step = make_train_step(cfg)
batch = synthetic_batch(cfg, 2, 32)
for _ in range(start, 400):
    state, m = step(state, batch)
    with open(vol + "/progress", "w") as f:
        f.write(str(int(state.step)))
    if drain.draining:
        drain.checkpoint_and_exit(vol + "/ckpts", state, grace_seconds=30.0)
    if os.path.exists(vol + "/stop"):
        break
    time.sleep(0.3)
    if drain.draining:
        drain.checkpoint_and_exit(vol + "/ckpts", state, grace_seconds=30.0)
with open(vol + "/final", "w") as f:
    f.write(f"resumed_from={start} final={int(state.step)}")
"""


@scenario("priority-preempt")
async def _priority_preempt(report, seed, tmp: Path) -> None:
    """Cluster-level priority preemption: the local fleet holds exactly ONE
    TPU slice (max_slices=1) and a priority-0 training run occupies it. A
    priority-50 run arrives, cannot place, and the scheduler reclaims
    capacity: the victim is cleanly drained (checkpoint + DRAIN_EXIT_CODE,
    reason preempted_by_scheduler), the high-priority run places on the
    freed slice and finishes, and the victim resumes from its drain
    checkpoint once capacity frees again. No chaos engine — the only
    "fault" is the scheduler doing its job."""
    from dstack_tpu.server import settings

    settings.RETRY_PENDING_RUN_DELAY = 0
    script = tmp / "train.py"
    await asyncio.to_thread(script.write_text, _VICTIM_TRAIN)
    mount = tmp / "mnt" / "ckpt"
    app, ctx, client = await _make_server(tpu_sim=["v5litepod-4"], max_slices=1)
    try:
        resp = await client.post(
            "/api/project/main/volumes/create",
            json_body={"configuration": {
                "type": "volume", "name": "chaos-ckpt", "backend": "local",
                "region": "local", "size": "1GB",
            }},
        )
        _expect(report, resp.status == 200, f"volume create failed: {resp.body!r}")
        body = _task_body(
            [f"PYTHONPATH={REPO_ROOT}:$PYTHONPATH exec python {script} {mount}"],
            "chaos-victim",
            resources={"tpu": "v5litepod-4"},
            retry={"on_events": ["interruption"], "duration": 600},
        )
        body["run_spec"]["configuration"]["volumes"] = [
            {"name": "chaos-ckpt", "path": str(mount)}
        ]
        resp = await client.post("/api/project/main/runs/submit", json_body=body)
        _expect(report, resp.status == 200, f"victim submit failed: {resp.body!r}")
        # The victim must be mid-training (checkpointable) before the
        # high-priority run shows up.
        progress = mount / "progress"
        for _ in range(600):
            if progress.exists():
                break
            await asyncio.sleep(0.2)
        _expect(report, progress.exists(), "victim never made training progress")

        body = _task_body(
            ["echo high-priority work done"],
            "chaos-highpri",
            resources={"tpu": "v5litepod-4"},
            priority=50,
        )
        resp = await client.post("/api/project/main/runs/submit", json_body=body)
        _expect(report, resp.status == 200, f"high-pri submit failed: {resp.body!r}")
        run = await _wait_run(
            client, "chaos-highpri", {"done", "failed", "terminated"}, 120
        )
        _expect(
            report, run["status"] == "done",
            f"high-pri run ended {run['status']}, want done (preemption placed it)",
        )

        # Let the resumed victim finish.
        await asyncio.to_thread((mount / "stop").write_text, "done")
        victim = await _wait_run(
            client, "chaos-victim", {"done", "failed", "terminated"}, 120
        )
        _expect(
            report, victim["status"] == "done",
            f"victim ended {victim['status']}, want done (resumed after preemption)",
        )
        subs = victim["jobs"][0]["job_submissions"]
        _expect(
            report, len(subs) == 2,
            f"victim has {len(subs)} submissions, want 2 (drained exactly once)",
        )
        _expect(
            report,
            subs[0]["termination_reason"] == "preempted_by_scheduler",
            f"victim first incarnation ended {subs[0]['termination_reason']},"
            " want preempted_by_scheduler",
        )
        final_path = mount / "final"
        resumed = -1
        if final_path.exists():
            final = await asyncio.to_thread(final_path.read_text)
            resumed = int(final.split("resumed_from=")[1].split()[0])
            report["details"]["final"] = final.strip()
        _expect(
            report, resumed > 0,
            f"victim resumed at step {resumed}, want > 0 (from the drain checkpoint)",
        )

        resp = await client.get("/metrics", token="")
        text = resp.body.decode()
        for metric, want in [
            ("dstack_tpu_run_scheduler_preemptions_total", 1),
            ("dstack_tpu_run_clean_drains_total", 1),
            ("dstack_tpu_run_restarts_total", 1),
            ("dstack_tpu_run_steps_lost_total", 0),
        ]:
            line = next(
                (
                    ln
                    for ln in text.splitlines()
                    if ln.startswith(metric + "{") and 'run="chaos-victim"' in ln
                ),
                None,
            )
            val = float(line.rsplit(" ", 1)[1]) if line else None
            _expect(report, val == want, f"/metrics {metric} = {val}, want {want}")
    finally:
        await app.shutdown()


_ELASTIC_TRAIN = """
import json, os, sys, time
vol = sys.argv[1]
os.environ["XLA_FLAGS"] = (
    "--xla_force_host_platform_device_count=4 " + os.environ.get("XLA_FLAGS", "")
)
import jax
jax.config.update("jax_platforms", "cpu")
# Synchronous dispatch: these sim trainers churn buffers (resize /
# drain-restore) while the host is oversubscribed by the whole drill
# fleet; CPU async dispatch can still touch freed buffers from its
# dispatch thread (observed SIGSEGV / malloc corruption under load).
jax.config.update("jax_cpu_enable_async_dispatch", False)
try:
    import jax.extend.backend as _jb
    _jb.clear_backends()
except Exception:
    pass
from dstack_tpu.parallel.mesh import rescale_accum_steps
from dstack_tpu.workloads.config import PRESETS
from dstack_tpu.workloads.sharding import make_mesh
from dstack_tpu.workloads.train import (
    init_train_state, make_train_step, read_resize_notice, synthetic_batch,
)
from dstack_tpu.workloads import checkpoint as ckpt

GLOBAL_BATCH = 12
cfg = PRESETS["tiny"]
devices = jax.devices()


_built = {}


def build(width, accum):
    # Cache per-width artifacts: re-expanding to a width already seen reuses
    # the mesh and compiled step (no recompile on rejoin).
    if (width, accum) not in _built:
        mesh = make_mesh(devices[:width], data=width)
        step = make_train_step(cfg, mesh, accum_steps=accum)
        batch = synthetic_batch(cfg, GLOBAL_BATCH, 32, mesh=mesh)
        _built[(width, accum)] = (mesh, step, batch)
    return _built[(width, accum)]


width, accum = 4, 3
mesh, step_fn, batch = build(width, accum)
state = init_train_state(cfg, jax.random.PRNGKey(0), mesh)
widths = [width]
steps_since_full = 0
for _ in range(200):
    notice = read_resize_notice()
    if notice and notice["width"] != width:
        # Shrink or re-expand: checkpoint, re-form the mesh at the new dp
        # width, reshard the state back in, rescale grad accumulation so
        # accum * width (the global batch) is invariant.
        ckpt.save(vol + "/ckpts", state, wait=True)
        ckpt.close_all()
        accum = rescale_accum_steps(accum, width, notice["width"])
        width = notice["width"]
        widths.append(width)
        mesh, step_fn, batch = build(width, accum)
        template = init_train_state(cfg, jax.random.PRNGKey(0), mesh)
        state = ckpt.restore_latest(vol + "/ckpts", template)
        steps_since_full = 0
    state, m = step_fn(state, batch)
    with open(vol + "/progress", "w") as f:
        f.write(str(int(state.step)))
    if width == 4 and len(widths) >= 3:
        steps_since_full += 1
        if steps_since_full >= 2:
            break
    time.sleep(0.3)
with open(vol + "/final", "w") as f:
    f.write(json.dumps({"widths": widths, "final_step": int(state.step)}))
"""


@scenario("elastic-resize")
async def _elastic_resize(report, seed, tmp: Path) -> None:
    """Elastic data-parallel recovery: a 4-host v5p-32 gang trains with
    elastic: true; chaos preempts worker 1 mid-run. Instead of restarting
    the gang, the server keeps the drained host's instance, notifies the
    survivors to re-form at width 3 (the rank-0 trainer reshards from its
    drain checkpoint and rescales grad accumulation to preserve the global
    batch), resubmits the lost rank in place, and re-expands to width 4
    when it rejoins. Rank 0 never restarts; no steps are lost."""
    from dstack_tpu.server import settings

    settings.RETRY_PENDING_RUN_DELAY = 0
    script = tmp / "train.py"
    await asyncio.to_thread(script.write_text, _ELASTIC_TRAIN)
    mount = tmp / "mnt" / "ckpt"
    engine = chaos.install(
        ChaosEngine(
            [
                {
                    "hook": "tick",
                    "action": "preempt",
                    "worker": 1,
                    "when_path_exists": str(mount / "progress"),
                    "message": "chaos: host maintenance",
                }
            ],
            seed=seed,
            name="elastic-resize",
        )
    )
    app, ctx, client = await _make_server(tpu_sim=["v5p-32"])
    try:
        await engine.start()
        resp = await client.post(
            "/api/project/main/volumes/create",
            json_body={"configuration": {
                "type": "volume", "name": "chaos-ckpt", "backend": "local",
                "region": "local", "size": "1GB",
            }},
        )
        _expect(report, resp.status == 200, f"volume create failed: {resp.body!r}")
        # Rank 0 execs the elastic trainer; other ranks model checkpointing
        # workers: exit DRAIN_EXIT_CODE on SIGTERM (a clean drain), park
        # until the trainer finishes otherwise.
        rank0 = f"PYTHONPATH={REPO_ROOT}:$PYTHONPATH exec python {script} {mount}"
        workers = (
            f"trap 'exit 113' TERM;"
            f" while [ ! -f {mount}/final ]; do sleep 0.2; done; echo rank done"
        )
        cmd = f'if [ "$JAX_PROCESS_ID" = "0" ]; then {rank0}; else {workers}; fi'
        body = _task_body(
            [cmd],
            "chaos-elastic",
            resources={"tpu": "v5p-32"},
            retry={"on_events": ["interruption"], "duration": 600},
            elastic=True,
        )
        body["run_spec"]["configuration"]["volumes"] = [
            {"name": "chaos-ckpt", "path": str(mount)}
        ]
        resp = await client.post("/api/project/main/runs/submit", json_body=body)
        _expect(report, resp.status == 200, f"submit failed: {resp.body!r}")
        run = await _wait_run(
            client, "chaos-elastic", {"done", "failed", "terminated"}, 240
        )
        _expect(report, run["status"] == "done", f"run ended {run['status']}, want done")
        _expect(report, engine.injected != [], "preempt event never fired")

        report["details"]["submissions"] = [
            {
                "job_num": job["job_spec"]["job_num"],
                "subs": [
                    {
                        "status": s["status"],
                        "reason": s.get("termination_reason"),
                        "exit": s.get("exit_status"),
                        "msg": s.get("termination_reason_message"),
                    }
                    for s in job["job_submissions"]
                ],
            }
            for job in run["jobs"]
        ]
        # Rank 0 must have survived on its FIRST submission — the whole
        # point of elastic mode is no full-gang restart.
        for job in run["jobs"]:
            subs = job["job_submissions"]
            num = job["job_spec"]["job_num"]
            want = 2 if num == 1 else 1
            _expect(
                report, len(subs) == want,
                f"job {num}: {len(subs)} submissions, want {want}",
            )

        final_path = mount / "final"
        widths = []
        if final_path.exists():
            import json as _json

            final = _json.loads(await asyncio.to_thread(final_path.read_text))
            widths = final["widths"]
            report["details"]["final"] = final
        _expect(
            report, widths == [4, 3, 4],
            f"trainer width history {widths}, want [4, 3, 4]"
            " (shrink on preemption, re-expand on rejoin)",
        )

        resp = await client.get("/metrics", token="")
        text = resp.body.decode()
        for metric, want in [
            ("dstack_tpu_run_elastic_resizes_total", 1),
            ("dstack_tpu_run_steps_lost_total", 0),
            ("dstack_tpu_run_restarts_total", 0),
        ]:
            line = next(
                (
                    ln
                    for ln in text.splitlines()
                    if ln.startswith(metric + "{") and 'run="chaos-elastic"' in ln
                ),
                None,
            )
            val = float(line.rsplit(" ", 1)[1]) if line else None
            _expect(report, val == want, f"/metrics {metric} = {val}, want {want}")
        report["details"]["injected"] = engine.injected
    finally:
        await engine.stop()
        await app.shutdown()
