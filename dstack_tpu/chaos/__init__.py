"""Chaos/resilience subsystem: deterministic fault injection for the
orchestrator's recovery paths.

Spot/preemptible capacity and maintenance events are the dominant failure
mode for multi-host TPU gangs; this package makes every recovery path
(preemption drain, gang resubmit, checkpoint resume, disconnect grace,
backend-API flakes) exercisable deterministically from the CPU test suite
and from a headless scenario runner (`python -m dstack_tpu.chaos`).

A process-global engine keeps the hook points one-liner cheap: production
code calls `maybe_inject(...)`, which is a no-op unless a test or scenario
installed an engine. See `docs/guides/resilience.md`.
"""

from typing import Optional

from dstack_tpu.chaos.engine import ChaosEngine, ChaosError, ChaosEvent

__all__ = [
    "ChaosEngine",
    "ChaosError",
    "ChaosEvent",
    "get_engine",
    "install",
    "maybe_inject",
    "uninstall",
]

_engine: Optional[ChaosEngine] = None


def install(engine: ChaosEngine) -> ChaosEngine:
    """Make `engine` the process-global chaos engine consulted by hooks."""
    global _engine
    _engine = engine
    return engine


def uninstall() -> None:
    global _engine
    _engine = None


def get_engine() -> Optional[ChaosEngine]:
    return _engine


async def maybe_inject(hook: str, **attrs) -> None:
    """Hook-point entry: no-op without an installed engine; otherwise may
    sleep (latency fault) or raise ChaosError (error fault)."""
    engine = _engine
    if engine is not None:
        await engine.inject(hook, **attrs)
