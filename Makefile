# Convenience targets. `make chaos` is the headless resilience drill:
# it exits nonzero if any scenario's run fails to recover.

PYTHON ?= python
PYTEST_ARGS ?= -q -m 'not slow' -p no:cacheprovider

.PHONY: test test-all chaos chaos-fast chaos-replica-kill chaos-worker-kill chaos-outage chaos-shard-kill dataplane lint lint-json capacity capacity-smoke capacity-multi bench-proxy bench-routing bench-serving bench-coldstart drill-disagg drill-rl bench-rl

test:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ $(PYTEST_ARGS)

test-all:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ -q -p no:cacheprovider

chaos:
	JAX_PLATFORMS=cpu $(PYTHON) -m dstack_tpu.chaos --all

chaos-fast:
	JAX_PLATFORMS=cpu $(PYTHON) -m dstack_tpu.chaos --scenario runner-flap

# Failure-isolation drills (docs/guides/multi-replica.md): control-plane
# replica SIGKILL with lease takeover, data-plane worker SIGKILL mid-SSE,
# and a full control-plane outage with degraded serving + epoch re-sync.
chaos-replica-kill:
	JAX_PLATFORMS=cpu $(PYTHON) -m dstack_tpu.chaos --scenario replica-kill-takeover

chaos-worker-kill:
	JAX_PLATFORMS=cpu $(PYTHON) -m dstack_tpu.chaos --scenario dataplane-worker-kill

chaos-outage:
	JAX_PLATFORMS=cpu $(PYTHON) -m dstack_tpu.chaos --scenario dataplane-outage

# Sharded-FSM drill: SIGKILL one of four replicas mid-probe; survivors
# must absorb its shards within one lease TTL of expiry with zero
# pre-expiry steals, and every in-flight run still completes.
chaos-shard-kill:
	JAX_PLATFORMS=cpu $(PYTHON) -m dstack_tpu.chaos --scenario shard-kill

# Standalone data-plane worker(s) against the local server DB.
dataplane:
	JAX_PLATFORMS=cpu $(PYTHON) -m dstack_tpu.dataplane --workers $(or $(WORKERS),1)

# Static analysis (docs/guides/static-analysis.md) + bytecode compile.
# --gate runs the whole pipeline in one process (shared parsed ASTs):
# main tree against the committed baseline, the analyzer's own package
# with the baseline ignored, good fixture tree clean, and the seeded
# bad fixture tree tripping every checker (exit 1 expected there).
lint:
	$(PYTHON) -m dstack_tpu.analysis --gate --jobs 4
	$(PYTHON) -m compileall -q dstack_tpu

lint-json:
	$(PYTHON) -m dstack_tpu.analysis dstack_tpu/ --json

# Full control-plane capacity probe (500 concurrent runs, native runner,
# real socket). Results land in CAPACITY_r06.json; see
# docs/guides/control-plane-tuning.md for how to read them.
capacity:
	JAX_PLATFORMS=cpu $(PYTHON) capacity_probe.py --runs 500 --out CAPACITY_r06.json

# Multi-replica scaling sweep: 1/2/4 replicas (1 in-process + N-1 real
# subprocesses) sharing one file-backed DB with hash-sharded FSM
# ownership. Per-arm aggregate runs/min lands in CAPACITY_r11.json.
capacity-multi:
	JAX_PLATFORMS=cpu $(PYTHON) capacity_probe.py --runs 500 --replicas 1,2,4 --out CAPACITY_r11.json

# Proxy data-plane benchmark: pooled+streamed fast path vs the legacy
# per-request-client buffered proxy, plus the multi-worker scaling and
# route-staleness arms (real dataplane subprocesses). Results land in
# BENCH_proxy_r09.json; see docs/guides/proxy-tuning.md and
# docs/guides/multi-replica.md for how to read them.
bench-proxy:
	JAX_PLATFORMS=cpu $(PYTHON) bench_proxy.py --out BENCH_proxy_r09.json

bench-routing:
	JAX_PLATFORMS=cpu $(PYTHON) bench_routing.py --out BENCH_routing_r18.json

# Serving-engine benchmark: chunked prefill + paged KV with prefix
# sharing, speculative-decoding arms, the r12 ragged-paged-attention
# cells, the r13 sharded (tensor-parallel bit-exactness/overhead) and
# disaggregation (prefill-flood decode-isolation) arms, and the r14
# multi-tenant arms (mixed-adapter LoRA batch vs merged-engine token
# equality + empty-pool overhead; noisy-neighbor steady-tenant TTFT
# with QoS on/off/no-flood), the r15 flight-recorder overhead arm
# (recorder-on vs recorder-off, the <2% tracing-always-on claim; run it
# alone with --arms recorder), and the r16 hierarchical-KV overcommit
# arm (host-RAM spill tier + slot preemption at 4x residency
# overcommit; run it alone with --arms overcommit). Results land in
# BENCH_serving_r16.json; see docs/guides/serving-tuning.md,
# docs/guides/multi-tenant.md and docs/guides/observability.md for how
# to read them.
bench-serving:
	JAX_PLATFORMS=cpu $(PYTHON) bench_serving.py --out BENCH_serving_r16.json

# Scale-from-zero cold-start decomposition: boots the native server as a
# fresh subprocess per arm (no cache / warm persistent compile cache /
# warm cache + packed parallel weight load / warm standby) and splits
# submit->first-token into stages from the ::dstack-tpu-stage:: markers.
# Asserts the warm-cache compile stage is >=5x smaller than cold and
# that the first post-/readyz request pays zero compiles (per-process
# compile-counter diff over /metrics). Results land in
# BENCH_coldstart_r20.json; see docs/guides/serving-tuning.md.
bench-coldstart:
	JAX_PLATFORMS=cpu $(PYTHON) bench_coldstart.py --out BENCH_coldstart_r20.json

# Prefill/decode disaggregation drill: two real worker processes over a
# 2-way model mesh each, KV handoffs over a socket. Asserts token
# bit-exactness vs a unified engine, end-to-end trace continuity (one
# trace_id spanning both tiers, phases telescoping per tier), clean
# cancel mid-handoff, stale-epoch reject + client refresh, and zero
# KV-block residue.
drill-disagg:
	JAX_PLATFORMS=cpu $(PYTHON) -m dstack_tpu.workloads.serving_disagg

# Podracer RL drill (docs/guides/rl.md): Sebulba-style actor gang
# (2 actor subprocesses) feeding an in-process learner, weight refresh
# over the framed-socket channel. Kills one actor mid-rollout, resolves
# it via elastic gang resize (accum-step rescale, zero learner
# restarts), then grows back to full width; asserts epoch convergence,
# the stage-marker timeline, and the RL /metrics series.
drill-rl:
	JAX_PLATFORMS=cpu $(PYTHON) -m dstack_tpu.workloads.rl_drill

# RL throughput benchmark: colocated (Anakin) loop, socket weight
# refresh vs a checkpoint-file refresh baseline. Records env-steps/s,
# learner step time, and weight-refresh latency in BENCH_rl_r17.json.
bench-rl:
	JAX_PLATFORMS=cpu $(PYTHON) bench_rl.py --out BENCH_rl_r17.json

# CI-sized variant: 40 runs in-process, asserts 0 failures + telemetry.
capacity-smoke:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/server/test_capacity_smoke.py -q -m capacity -p no:cacheprovider
