# Convenience targets. `make chaos` is the headless resilience drill:
# it exits nonzero if any scenario's run fails to recover.

PYTHON ?= python
PYTEST_ARGS ?= -q -m 'not slow' -p no:cacheprovider

.PHONY: test test-all chaos chaos-fast lint

test:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ $(PYTEST_ARGS)

test-all:
	JAX_PLATFORMS=cpu $(PYTHON) -m pytest tests/ -q -p no:cacheprovider

chaos:
	JAX_PLATFORMS=cpu $(PYTHON) -m dstack_tpu.chaos --all

chaos-fast:
	JAX_PLATFORMS=cpu $(PYTHON) -m dstack_tpu.chaos --scenario runner-flap

lint:
	$(PYTHON) -m compileall -q dstack_tpu
