"""Serving-engine throughput benchmark on the real chip.

The continuous-batching engine (workloads/serving.py) exists to multiplex
many decode streams over one chip; its batch-1 numbers (519 tok/s int8 /
416 bf16, round 3) only proved correctness overhead. This measures the
reason it exists: aggregate tokens/s and tail latency at 1/8/16/32
concurrent streams, bf16 vs int8 weight-only quantization.

Metrics per scenario:
- agg_tok_s    — total generated tokens / wall time (the capacity number)
- ttft_p50/p95 — submit -> first token, ms (includes prefill + queueing;
  on a tunneled dev chip this carries the tunnel RTT)
- tpt_p50/p95  — per-stream EFFECTIVE token cadence, ms: (last_token_ts -
  first_token_ts) / (n-1) for each stream, percentiles across streams.
  Tokens arrive in steps_per_sync-sized bursts, so raw inter-token
  deltas are mostly ~0 and their percentiles said nothing (the r4 file
  published tpt_p50=0.0); the per-stream cadence is the number a client
  actually experiences.

Each scenario also records the engine's own view of the run: the TTFT
breakdown (queue wait -> prefill -> first chunk, from the scheduler's
EWMA gauges) and the decode/prefill/idle utilization split — the numbers
that show whether prefill is stealing decode time (the r05 failure mode:
agg tok/s flat 675.8 -> 669.2 going 16 -> 32 streams while TTFT p95 hit
4.6 s, classic prefill head-of-line blocking, fixed by the overlapped
scheduler).

The admission-control scenario exercises shedding: slots oversubscribed
2x with `max_pending` bounded — overflow is rejected with a Retry-After
hint and the client retries; TTFT of ACCEPTED requests stays bounded
instead of the 10.8 s p50 measured unbounded in r4. The prefill-heavy
scenario (long prompts, short generations) isolates prefill/decode
overlap: sequential admission serializes the long prefills in front of
every decode chunk, overlap hides them behind it.

Round 8 adds the paged-KV scenarios: an 8-stream burst arriving on a
WARMED shared system prompt (the TTFT case chunked prefill + prefix
caching exists for — acceptance: burst TTFT p95 < 2x single-stream TTFT
p50), and a shared-prefix accounting scenario (N streams over one common
prefix: cache-hit streams must show a >=50% prefill-compute drop, and
peak block-pool occupancy must come in far under the dense per-slot
equivalent — the "more live slots in the same KV budget" claim). Every
scenario now also reports the engine's prefix-cache hit rate and block
pool occupancy.

Round 10 adds draft-model speculative decoding: a high-acceptance arm
(drafter = int8 of the target) and an adversarial arm (random-init
drafter of the same shape) each run against a non-speculative baseline
at the same steps_per_sync=1 sync cadence, reporting acceptance rate,
accepted-tokens-per-target-step (every target forward — verify or plain
step — emits exactly one non-draft token, so the metric is
tokens / (tokens - accepted)), and the wall-clock tok/s ratio vs the
baseline arm.

Round 12 replaces the dense-view gather entirely: attention now runs
raggedly over the block tables (workloads/paged_attention.py), so no
consumer — decode, chunked prefill, draft, or verify — ever gathers a
slot's blocks into a `(max_len, KV, hd)` scratch, and the r10
cross-chunk view cache (plus the HBM it pinned) is gone. The
r10_comparison_note quantifies the recovery on the cell that paid the
gather hardest (batch-1 bf16 steps_per_sync=4), and the top-level
hbm_headroom_bytes / kv_budget_stretch fields account for the freed
carried-view memory as extra KV block budget.

Round 13 adds the sharded and disaggregated arms. The sharded arm runs
a 2-way tensor-parallel engine (column-parallel specs over a virtual
2-device CPU mesh, in a subprocess so the device count is controlled)
against an unsharded control in the SAME subprocess, asserting token
bit-exactness and reporting the relative throughput (on one physical
core the mesh is pure overhead; the arm prices the sharding machinery,
not a speedup). The disaggregation arm spawns real prefill/decode
worker processes (workloads/serving_disagg.py), floods the
CPU-deprioritized prefill worker with long-prompt one-token requests
mid-decode, and measures decode TPT p95 as the per-stream effective
cadence (median over alternating base/flood repetitions): the
isolation claim is that the disagg decode worker's flood/baseline p95
ratio stays near 1 while a unified control engine — same streams, same
flood, one loop — degrades (its prefill chunks serialize with decode
at every boundary).

Round 14 adds the multi-tenant arms. The LoRA-multiplex arm loads three
rank-8 adapters into one engine's device pool, decodes a mixed batch
(every tenant plus the base model concurrently) and asserts each
stream's tokens equal its tenant's merge_lora'd reference; it then
prices the consolidation (mixed batch vs the same four requests served
one at a time) and the adapter_id=-1 fast path (a LoRA-enabled engine
with an empty pool vs the plain pre-LoRA engine — the zero-cost claim).
The noisy-neighbor arm runs three steady tenants against one tenant
flooding long-prompt requests at ~10x its token-bucket rate and
measures steady-tenant TTFT p95 (from when the tenant WANTED to submit,
so queueing and shedding costs are visible) in three phases: no flood,
flood with no QoS, and flood behind a QoSGate (token buckets + DRR
admission): with QoS on the flood is absorbed by shedding and steady
TTFT stays near the no-flood baseline, while the QoS-off control shows
the head-of-line damage the gate prevents.

Round 15 adds the recorder-overhead arm: identical 8-stream traffic on
a flight-recorder-off engine (trace_ring=0) vs recorder-on at the
deployment shape (256-slot ring + 50 ms tail capture), alternating
order with medians — the claim that leaving per-request phase tracing
on in production costs <2% on both aggregate tok/s and TTFT p95.

Round 16 adds the overcommit arm: hierarchical KV cache with a host-RAM
spill tier and slot preemption. One engine overcommits residency 4x
(`max_resident_slots` at 1/4 of its slots) over a device pool too small
to retain the shared prefix under churn; against a resident-only
baseline it holds the prefix-hit rate at 1.0 (spilled blocks swap back
from host RAM instead of missing), its post-churn TTFT undercuts the
baseline's cold re-prefill, and a controlled engine.preempt mid-decode
times the wholesale chain swap-in against the cold prefill of the same
prompt shape.

Writes BENCH_serving_r16.json (override with --out) and prints one JSON
line per scenario. Regression guard: tests/test_serving.py pins
engine==one-shot decode numerics; this file pins the performance claim
(continuous batching must show a multi-x aggregate over batch-1, TTFT
p95 at 32 streams must stay bounded while agg tok/s holds the 16-stream
plateau, and r12's ragged path must hold r06's 1-stream aggregate
within 5% where r10 measured -63.6%).
"""

import argparse
import json
import queue
import statistics
import threading
import time
from typing import Dict, List

import jax
import jax.numpy as jnp

from dstack_tpu.workloads.config import PRESETS
from dstack_tpu.workloads.serving import ServingEngine
from dstack_tpu.workloads.transformer import init_params

PROMPT_LEN = 64
NEW_TOKENS = 128
MAX_LEN = 512
SLOTS = 16  # engine batch width; streams beyond this queue
# Prompt tokens stay strictly inside the model's vocab (set in main()
# from the chosen preset). Out-of-vocab ids silently clamp in the embed
# take, collapsing every stream onto one embedding — timing-identical,
# but it makes generated content degenerate, which fakes the spec arms'
# acceptance (any drafter agrees on a fixed point).
TOKEN_MOD = 30000


def _drain_timed(q: "queue.Queue[object]", t0: float, n_expected: int) -> Dict:
    ts: List[float] = []
    while True:
        item = q.get(timeout=600)
        if item is None:
            break
        if isinstance(item, BaseException):
            raise item
        ts.append(time.perf_counter())
    assert len(ts) == n_expected, len(ts)
    # Effective per-token cadence for THIS stream: tokens land in
    # steps_per_sync bursts, so per-delta percentiles are ~0/meaningless;
    # span/(n-1) is the cadence a client sees.
    cadence = (ts[-1] - ts[0]) / (len(ts) - 1) * 1e3 if len(ts) > 1 else 0.0
    return {"ttft": (ts[0] - t0) * 1e3, "cadence": cadence, "n": len(ts)}


def _pct(xs, p):
    return xs[min(len(xs) - 1, int(p * len(xs)))]


def run_scenario(engine: ServingEngine, streams: int, retry: bool = False,
                 prompt_len: int = None, new_tokens: int = None) -> Dict:
    from dstack_tpu.workloads.serving import EngineOverloadedError

    prompt_len = PROMPT_LEN if prompt_len is None else prompt_len
    new_tokens = NEW_TOKENS if new_tokens is None else new_tokens
    prompts = [
        [((i * 37 + j * 13) % TOKEN_MOD) + 1 for j in range(prompt_len)]
        for i in range(streams)
    ]
    results: List[Dict] = [None] * streams  # type: ignore
    retries = [0] * streams
    stats0 = engine.stats()  # counter snapshot: per-scenario util diffs
    t0 = time.perf_counter()

    def worker(i: int) -> None:
        while True:
            # TTFT is measured from the submit that was ACCEPTED: with
            # admission control the client's total latency is visible in
            # `retries` + Retry-After, while TTFT shows the bounded
            # in-engine latency SLO.
            t_submit = time.perf_counter()
            try:
                q = engine.submit(prompts[i], max_new_tokens=new_tokens)
            except EngineOverloadedError as e:
                if not retry:
                    raise
                retries[i] += 1
                time.sleep(e.retry_after)
                continue
            results[i] = _drain_timed(q, t_submit, new_tokens)
            return

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(streams)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    ttfts = sorted(r["ttft"] for r in results)
    cadences = sorted(r["cadence"] for r in results)
    total = sum(r["n"] for r in results)

    # The engine's own breakdown of the TTFT it just served, from the
    # summary counters diffed across the scenario (exact per-scenario
    # means — the EWMA gauges carry compile-spike history from warmup):
    # queue wait (submit -> admission), prefill (admission -> first
    # token, which under the overlapped scheduler includes the decode
    # chunk it hid behind), and the residual of the measured client-side
    # p50. Plus the decode/prefill/idle wall-time split — the gauges
    # that pin "prefill never stalls decode" on hardware-free CI where
    # absolute tok/s means nothing.
    stats = engine.stats()
    n_adm = max(1, stats["admitted_total"] - stats0["admitted_total"])
    queue_ms = (
        stats["queue_wait_seconds_sum"] - stats0["queue_wait_seconds_sum"]
    ) / n_adm * 1e3
    prefill_ms = (
        stats["prefill_seconds_sum"] - stats0["prefill_seconds_sum"]
    ) / n_adm * 1e3
    ttft_p50 = _pct(ttfts, 0.50)
    spans = {
        k: stats[f"{k}_seconds_total"] - stats0[f"{k}_seconds_total"]
        for k in ("decode", "prefill", "idle")
    }
    span_total = sum(spans.values()) or 1.0
    # Prefix-cache effectiveness + pool occupancy over the scenario (the
    # r08 paged-KV columns): hit rate across this scenario's admissions,
    # prompt tokens the chunked prefill actually computed vs reused from
    # cache, and the pool's end-of-scenario occupancy.
    lookups = (stats["prefix_cache_hits_total"]
               - stats0["prefix_cache_hits_total"]
               + stats["prefix_cache_misses_total"]
               - stats0["prefix_cache_misses_total"])
    hits = stats["prefix_cache_hits_total"] - stats0["prefix_cache_hits_total"]
    out = {
        "streams": streams,
        "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "agg_tok_s": round(total / wall, 1),
        "ttft_p50_ms": round(ttft_p50, 1),
        "ttft_p95_ms": round(_pct(ttfts, 0.95), 1),
        "tpt_p50_ms": round(_pct(cadences, 0.50), 2),
        "tpt_p95_ms": round(_pct(cadences, 0.95), 2),
        "wall_s": round(wall, 2),
        "ttft_breakdown_ms": {
            "queue_wait": round(queue_ms, 1),
            "prefill": round(prefill_ms, 1),
            "first_chunk_residual": round(
                max(0.0, ttft_p50 - queue_ms - prefill_ms), 1
            ),
        },
        "util": {k: round(v / span_total, 4) for k, v in spans.items()},
        "prefix_hit_rate": round(hits / lookups, 3) if lookups else 0.0,
        "prefill_tokens_computed": (
            stats["prefill_tokens_computed_total"]
            - stats0["prefill_tokens_computed_total"]
        ),
        "prefix_tokens_reused": (
            stats["prefix_tokens_reused_total"]
            - stats0["prefix_tokens_reused_total"]
        ),
        "kv_blocks": {"total": stats["kv_blocks_total"],
                      "in_use": stats["kv_blocks_in_use"],
                      "cached": stats["kv_blocks_cached"]},
    }
    if retry:
        out["sheds"] = sum(retries)
        out["max_pending"] = engine.max_pending
    return out


def run_spec_scenario(engine: ServingEngine, streams: int,
                      new_tokens: int = None) -> Dict:
    """run_scenario plus the speculation columns diffed over the run.

    `accepted_tokens_per_target_step` uses the identity that every
    target forward pass — a (k+1)-wide verify or a plain decode step —
    emits exactly ONE token that did not come from an accepted draft
    (the bonus/correction token, or the plain step's sample): target
    steps = emitted - accepted, so the metric is
    emitted / (emitted - accepted). 1.0 = plain decode; the r10
    acceptance bar is >= 1.5 on the high-acceptance arm."""
    s0 = engine.stats()
    out = run_scenario(engine, streams, new_tokens=new_tokens)
    s1 = engine.stats()
    proposed = (s1["spec_tokens_proposed_total"]
                - s0["spec_tokens_proposed_total"])
    accepted = (s1["spec_tokens_accepted_total"]
                - s0["spec_tokens_accepted_total"])
    # First token of each stream comes from prefill finalize, not a
    # decode/verify step.
    emitted = streams * (out["new_tokens"] - 1)
    out.update({
        "spec": {
            "rounds": s1["spec_rounds_total"] - s0["spec_rounds_total"],
            "fallback_rounds": (s1["spec_fallback_rounds_total"]
                                - s0["spec_fallback_rounds_total"]),
            "proposed": proposed,
            "accepted": accepted,
            "acceptance_rate": round(accepted / proposed, 3)
            if proposed else 0.0,
            "accepted_tokens_per_target_step": round(
                emitted / max(1, emitted - accepted), 2
            ),
            "draft_len_mean": s1["spec_draft_len_mean"],
            "draft_seconds": round(
                s1["spec_draft_seconds_total"]
                - s0["spec_draft_seconds_total"], 3
            ),
            "verify_seconds": round(
                s1["spec_verify_seconds_total"]
                - s0["spec_verify_seconds_total"], 3
            ),
        },
    })
    return out


def _shared_prefix_prompts(streams, prefix_len, suffix_len):
    prefix = [((j * 31) % TOKEN_MOD) + 1 for j in range(prefix_len)]
    return [
        prefix + [((i * 7 + j * 3) % TOKEN_MOD) + 1 for j in range(suffix_len)]
        for i in range(streams)
    ]


def run_shared_prefix_scenario(engine: ServingEngine, streams: int,
                               prefix_len: int, suffix_len: int,
                               new_tokens: int) -> Dict:
    """N streams over one common prompt prefix: one cold pass fills the
    prefix cache, then the remaining streams run concurrently as cache
    hits. Reports the per-stream prefill compute drop (the >=50%
    acceptance bar) and peak pool occupancy vs the dense per-slot
    equivalent (the "more live slots in the same KV budget" claim)."""
    prompts = _shared_prefix_prompts(streams, prefix_len, suffix_len)
    prompt_len = prefix_len + suffix_len

    def run_one(p):
        t = time.perf_counter()
        return _drain_timed(
            engine.submit(p, max_new_tokens=new_tokens), t, new_tokens
        )

    # Warm compile caches WITHOUT touching the measured prefix: shifted
    # token content has the same shapes (full-prompt bucket, then a
    # suffix-sized bucket via its own prefix hit) but can never match
    # the real prompts in the cache.
    run_one([(t % 29999) + 2 for t in prompts[0]])
    run_one([(t % 29999) + 2 for t in prompts[1]])
    s0 = engine.stats()
    baseline_blocks = s0["kv_blocks_in_use"]  # warmup's cached leftovers
    run_one(prompts[0])  # cold: computes the full prompt, fills the cache
    s_cold = engine.stats()
    cold_tokens = (s_cold["prefill_tokens_computed_total"]
                   - s0["prefill_tokens_computed_total"])

    # Hit pass: the rest of the streams at once, sampling peak occupancy.
    peak = [0]
    stop = threading.Event()

    def sampler():
        while not stop.is_set():
            peak[0] = max(
                peak[0],
                engine.stats()["kv_blocks_in_use"] - baseline_blocks,
            )
            time.sleep(0.005)

    st = threading.Thread(target=sampler)
    st.start()
    results = [None] * (streams - 1)
    t0 = time.perf_counter()

    def worker(i):
        results[i] = run_one(prompts[i + 1])

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(streams - 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    stop.set()
    st.join()
    s_hit = engine.stats()
    hit_tokens = (s_hit["prefill_tokens_computed_total"]
                  - s_cold["prefill_tokens_computed_total"])
    per_hit = hit_tokens / (streams - 1)
    bs = s_hit["kv_block_size"]
    # Dense equivalent: every live stream pins ceil(prompt+gen / bs)
    # blocks of PRIVATE cache — no sharing possible.
    dense_blocks = streams * -(-(prompt_len + new_tokens) // bs)
    ttfts = sorted(r["ttft"] for r in results)
    return {
        "shape": "shared_prefix",
        "streams": streams,
        "prefix_len": prefix_len,
        "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "agg_tok_s": round((streams - 1) * new_tokens / wall, 1),
        "ttft_p50_ms": round(_pct(ttfts, 0.50), 1),
        "ttft_p95_ms": round(_pct(ttfts, 0.95), 1),
        "prefill_tokens_cold": cold_tokens,
        "prefill_tokens_per_hit": round(per_hit, 1),
        "prefill_compute_drop": round(1.0 - per_hit / cold_tokens, 3),
        "prefix_hit_rate": round(
            (s_hit["prefix_cache_hits_total"] - s_cold["prefix_cache_hits_total"])
            / (streams - 1), 3
        ),
        "kv_blocks_peak_in_use": peak[0],
        "kv_blocks_dense_equivalent": dense_blocks,
        "kv_budget_stretch": round(dense_blocks / max(1, peak[0]), 2),
    }


def run_warmed_burst_scenario(engine: ServingEngine, streams: int,
                              prefix_len: int, suffix_len: int,
                              new_tokens: int) -> Dict:
    """The TTFT case the tentpole exists for: `streams` requests land AT
    ONCE on an engine whose shared system prompt is already cached (one
    warmup request ran it). Chunked prefill bounds each boundary's
    stall and the cache skips the prefix, so burst TTFT p95 must stay
    under 2x the single-stream TTFT p50 — the median TTFT of a lone
    request with nothing in the cache to share, i.e. the full-prefill
    cost every one of these streams would have paid without sharing
    (the r06-comparable baseline; the warmed single is also reported)."""
    prompt_len = prefix_len + suffix_len
    prompts = _shared_prefix_prompts(streams + 2, prefix_len, suffix_len)

    def run_one(p):
        t = time.perf_counter()
        return _drain_timed(
            engine.submit(p, max_new_tokens=new_tokens), t, new_tokens
        )

    def cold_prompt(seed):
        # Unique content per seed: never matches the cache or each other
        # beyond coincidental single blocks.
        return [((seed * 101 + j * 17) % 29000) + 1 for j in range(prompt_len)]

    run_one(cold_prompt(991))  # compile the full-prompt bucket (unmeasured)
    singles = sorted(run_one(cold_prompt(7 + k))["ttft"] for k in range(5))
    single_p50 = singles[len(singles) // 2]

    run_one(prompts[0])  # warm the shared prefix into the cache
    run_one(prompts[streams + 1])  # first hit: compiles the suffix bucket
    # Warmed singles: prefix hit + distinct cold suffix each (reusing
    # one prompt would cache its suffix and overstate the hit).
    prefix = prompts[0][:prefix_len]
    warmed = sorted(
        run_one(prefix + [((k * 13 + j * 5) % 28000) + 1
                          for j in range(suffix_len)])["ttft"]
        for k in (101, 103, 107)
    )

    # Submit the whole burst from this thread (sub-ms apart, so it lands
    # in one admission boundary), then drain each stream concurrently.
    results = [None] * streams
    t0 = time.perf_counter()
    submitted = []
    for i in range(streams):
        t_sub = time.perf_counter()
        submitted.append(
            (engine.submit(prompts[i + 1], max_new_tokens=new_tokens), t_sub)
        )
    threads = [
        threading.Thread(
            target=lambda i=i: results.__setitem__(
                i, _drain_timed(submitted[i][0], submitted[i][1], new_tokens)
            )
        )
        for i in range(streams)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    ttfts = sorted(r["ttft"] for r in results)
    p95 = _pct(ttfts, 0.95)
    return {
        "shape": "warmed_burst",
        "streams": streams,
        "prefix_len": prefix_len,
        "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "agg_tok_s": round(streams * new_tokens / wall, 1),
        "single_ttft_p50_ms": round(single_p50, 1),
        "warmed_single_ttft_p50_ms": round(warmed[len(warmed) // 2], 1),
        "ttft_p50_ms": round(_pct(ttfts, 0.50), 1),
        "ttft_p95_ms": round(p95, 1),
        "ttft_p95_vs_single_p50": round(p95 / max(1e-9, single_p50), 2),
        # The <2x bar targets the hardware shape, where a lone 512+32
        # prefill costs hundreds of ms (r06 measured 339 ms TTFT p50 at
        # just 4 streams) and the burst's cache-hit chunks cost tens.
        # At CPU-tiny scale the whole cold prefill is ~4 ms, so the
        # ratio degenerates into (8 serialized ~2 ms chunk dispatches) /
        # (per-request host overhead) — it measures Python, not the
        # cache. The absolute row is the evidence: burst p95 stays
        # ~20 ms where r06-style unshared admission queued for 100s of
        # ms.
        "bar_scope": "ratio bar applies on_tpu; CPU-tiny is"
                     " host-overhead-bound",
    }


# ----------------------------------------------- r13: sharded + disagg arms

_SHARDED_ARM_SRC = """
import json, time
import jax
from dstack_tpu.workloads.config import PRESETS
from dstack_tpu.workloads.serving import ServingEngine
from dstack_tpu.workloads.sharding import make_mesh
from dstack_tpu.workloads.transformer import init_params

assert len(jax.devices()) == 2, jax.devices()
cfg = PRESETS["tiny"]
params = init_params(cfg, jax.random.PRNGKey(0))
prompts = [[((i * 37 + j * 13) % 500) + 1 for j in range(64)]
           for i in range(4)]


def drain(q):
    toks = []
    while True:
        t = q.get(timeout=600)
        if t is None:
            return toks
        if isinstance(t, BaseException):
            raise t
        toks.append(int(t))


def run(mesh):
    eng = ServingEngine(cfg, params, slots=4, max_len=256,
                        kv_block_size=16, steps_per_sync=4, mesh=mesh)
    try:
        drain(eng.submit(prompts[0], 64))  # warm the jit caches
        t0 = time.perf_counter()
        outs = [eng.submit(p, 64) for p in prompts]
        streams = [drain(o) for o in outs]
        dt = time.perf_counter() - t0
        return streams, sum(len(s) for s in streams) / dt
    finally:
        eng.close()


base_streams, base_tok_s = run(None)
sh_streams, sh_tok_s = run(make_mesh(jax.devices(), model=2))
print(json.dumps({
    "bit_exact": base_streams == sh_streams,
    "unsharded_tok_s": round(base_tok_s, 2),
    "sharded_tok_s": round(sh_tok_s, 2),
}))
"""


def run_sharded_arm(out: Dict) -> None:
    """2-way tensor-parallel engine vs unsharded control, in a subprocess
    pinned to exactly 2 virtual CPU devices. On one physical core the
    mesh buys nothing — the arm pins bit-exactness and prices the
    sharding machinery (jit with explicit shardings, replicated
    contractions); the speedup claim belongs to real multi-chip runs."""
    import os
    import pathlib
    import subprocess
    import sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    repo = str(pathlib.Path(__file__).resolve().parent)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (repo, env.get("PYTHONPATH")) if p
    )
    proc = subprocess.run(
        [sys.executable, "-c", _SHARDED_ARM_SRC], env=env, cwd=repo,
        capture_output=True, text=True, timeout=900,
    )
    if proc.returncode != 0:
        raise RuntimeError(f"sharded arm failed: {proc.stderr[-2000:]}")
    r = json.loads(proc.stdout.strip().splitlines()[-1])
    s = {
        "arm": "sharded_tp2", "model": "tiny", "streams": 4,
        "bit_exact_vs_unsharded": r["bit_exact"],
        "unsharded_tok_s": r["unsharded_tok_s"],
        "sharded_tok_s": r["sharded_tok_s"],
        "tok_s_ratio": round(r["sharded_tok_s"] / r["unsharded_tok_s"], 3),
    }
    assert s["bit_exact_vs_unsharded"], "sharded engine diverged"
    out["scenarios"].append(s)
    print(json.dumps(s), flush=True)


def _cadence_p95_ms(times_by_stream: List[List[float]]) -> float:
    """p95 across streams of each stream's effective token cadence,
    span/(n-1) — the same TPT definition every other scenario in this
    file reports. Raw inter-token gaps are a steps_per_sync burst
    pattern whose p95 is the single worst chunk boundary (pure noise on
    a shared core); the cadence integrates over the whole decode, which
    is exactly the quantity a sustained prefill flood inflates."""
    cadences = sorted(
        (ts[-1] - ts[0]) / (len(ts) - 1) * 1e3
        for ts in times_by_stream if len(ts) > 1
    )
    return _pct(cadences, 0.95) if cadences else 0.0


# 4 full slots x 96 tokens: enough decode work per chunk that the
# cadence reflects sustained interference, not one-core scheduling
# latency around a near-idle loop.
DISAGG_STREAMS = 4
DISAGG_PROMPT = 64
DISAGG_NEW = 96
FLOOD_PROMPT = 192


def _bench_prompt(seed: int, length: int) -> List[int]:
    return [((seed * 37 + j * 13) % TOKEN_MOD) + 1 for j in range(length)]


def _disagg_phase(pre, dec, rid0: int, flood: bool) -> Dict:
    """One measured window against the worker pair: DISAGG_STREAMS decode
    streams, optionally under a continuous long-prompt one-token flood
    aimed at the prefill worker (each flood request completes locally
    there — pure prefill pressure, zero decode-side work)."""
    from dstack_tpu.workloads.serving_disagg import wait_prefill

    stop = threading.Event()
    flood_counts = {"submitted": 0, "completed": 0}

    def _flood() -> None:
        frid = rid0 + 1000
        while not stop.is_set():
            pre.send({"kind": "generate", "id": frid,
                      "prompt": _bench_prompt(frid, FLOOD_PROMPT),
                      "max_new_tokens": 1})
            flood_counts["submitted"] += 1
            ev = pre.stream(frid).get(timeout=600)  # back-to-back pressure
            if ev["kind"] == "prefill_tokens":
                flood_counts["completed"] += 1
            frid += 1

    flooder = None
    if flood:
        flooder = threading.Thread(target=_flood, daemon=True)
        flooder.start()
        time.sleep(0.5)  # let the flood reach the prefill loop first
    rids = list(range(rid0, rid0 + DISAGG_STREAMS))
    for rid in rids:
        pre.send({"kind": "generate", "id": rid,
                  "prompt": _bench_prompt(rid, DISAGG_PROMPT),
                  "max_new_tokens": DISAGG_NEW})
    times: List[List[float]] = []
    for rid in rids:
        res = wait_prefill(pre, rid, timeout=600)
        assert res["kind"] == "prefill_done", res
        ts: List[float] = []
        q = dec.stream(rid)
        while True:
            ev = q.get(timeout=600)
            if ev["kind"] == "done":
                break
            assert ev["kind"] == "token", ev
            ts.append(ev["t_recv"])
        assert len(ts) == DISAGG_NEW, len(ts)
        times.append(ts)
    stop.set()
    if flooder is not None:
        flooder.join(timeout=600)
    return {"tpt_p95_ms": round(_cadence_p95_ms(times), 2), **flood_counts}


def _unified_phase(engine: ServingEngine, flood: bool) -> Dict:
    """The control: same streams + same flood, one engine, one loop —
    every flood prefill chunk serializes with decode at a boundary."""
    stop = threading.Event()
    flood_counts = {"submitted": 0, "completed": 0}

    def _flood() -> None:
        frid = 5000
        while not stop.is_set():
            q = engine.submit(_bench_prompt(frid, FLOOD_PROMPT), 1)
            flood_counts["submitted"] += 1
            while q.get(timeout=600) is not None:
                pass
            flood_counts["completed"] += 1
            frid += 1

    flooder = None
    if flood:
        flooder = threading.Thread(target=_flood, daemon=True)
        flooder.start()
        time.sleep(0.5)
    outs = [engine.submit(_bench_prompt(i, DISAGG_PROMPT), DISAGG_NEW)
            for i in range(DISAGG_STREAMS)]
    times: List[List[float]] = []
    for q in outs:
        ts: List[float] = []
        while True:
            t = q.get(timeout=600)
            if t is None:
                break
            if isinstance(t, BaseException):
                raise t
            ts.append(time.monotonic())
        assert len(ts) == DISAGG_NEW, len(ts)
        times.append(ts)
    stop.set()
    if flooder is not None:
        flooder.join(timeout=600)
    return {"tpt_p95_ms": round(_cadence_p95_ms(times), 2), **flood_counts}


def run_disagg_arm(out: Dict) -> None:
    """Decode-isolation measurement: flood/baseline decode TPT p95 ratio
    for the disaggregated pair vs the unified control. The prefill
    worker runs CPU-deprioritized (nice 19) — the single-host stand-in
    for the split's physical isolation on real TPU workers."""
    from dstack_tpu.workloads.serving_disagg import WorkerProc, _free_port

    reps = 5  # alternate base/flood per rep, report medians: a one-core
    # container's host-load drift otherwise dominates a single pair

    def _median(phases):
        counts = {"submitted": sum(p["submitted"] for p in phases),
                  "completed": sum(p["completed"] for p in phases)}
        return {"tpt_p95_ms": statistics.median(
            p["tpt_p95_ms"] for p in phases), **counts}

    transfer_port = _free_port()
    # 8 slots / 4 measured streams on BOTH topologies: the spare slots
    # are what lets the unified engine ADMIT the flood mid-decode (at 4/4
    # the flood would just sit in the pending queue and the control shows
    # nothing); the disagg decode worker has the same spares, but the
    # one-token flood completes on the prefill worker and never reaches
    # it — that asymmetry is the isolation under test.
    dec = WorkerProc("decode", preset="tiny", max_len=256, slots=8,
                     transfer_port=transfer_port)
    pre = WorkerProc("prefill", preset="tiny", max_len=256, slots=8,
                     connect_port=transfer_port, nice=19)
    try:
        dec.connect()
        pre.connect()
        _disagg_phase(pre, dec, rid0=0, flood=False)   # warm the jits
        bases, floods = [], []
        for rep in range(reps):
            bases.append(_disagg_phase(
                pre, dec, rid0=100 * (2 * rep + 1), flood=False))
            floods.append(_disagg_phase(
                pre, dec, rid0=100 * (2 * rep + 2), flood=True))
        base, flood = _median(bases), _median(floods)
        pre_stats = pre.stats()["stats"]
    finally:
        pre.close()
        dec.close()

    engine = ServingEngine(PRESETS["tiny"],
                           init_params(PRESETS["tiny"],
                                       jax.random.PRNGKey(0)),
                           slots=8, max_len=256, kv_block_size=16)
    try:
        _unified_phase(engine, flood=False)            # warm the jits
        ubases, ufloods = [], []
        for _ in range(reps):
            ubases.append(_unified_phase(engine, flood=False))
            ufloods.append(_unified_phase(engine, flood=True))
        ubase, uflood = _median(ubases), _median(ufloods)
    finally:
        engine.close()

    def ratio(f, b):
        return round(f["tpt_p95_ms"] / b["tpt_p95_ms"], 3) \
            if b["tpt_p95_ms"] else 0.0

    s = {
        "arm": "disagg_isolation", "model": "tiny", "slots": 8,
        "streams": DISAGG_STREAMS, "new_tokens": DISAGG_NEW,
        "flood_prompt_len": FLOOD_PROMPT, "prefill_nice": 19,
        "reps": reps,
        "disagg_tpt_p95_ms": base["tpt_p95_ms"],
        "disagg_tpt_p95_flood_ms": flood["tpt_p95_ms"],
        "disagg_flood_ratio": ratio(flood, base),
        "disagg_flood_completed": flood["completed"],
        "unified_tpt_p95_ms": ubase["tpt_p95_ms"],
        "unified_tpt_p95_flood_ms": uflood["tpt_p95_ms"],
        "unified_flood_ratio": ratio(uflood, ubase),
        "unified_flood_completed": uflood["completed"],
        "kv_handoffs_sent_total": pre_stats["kv_handoffs_sent_total"],
        "kv_transfer_bytes_total": pre_stats["kv_transfer_bytes_total"],
    }
    out["scenarios"].append(s)
    print(json.dumps(s), flush=True)


# --- r14: multi-tenant arms ------------------------------------------------

LORA_TENANTS = ("acme", "globex", "initech")
LORA_RANK = 8
LORA_NEW = 64
# The exactness batch is shorter: every extra greedy token is another
# chance for a bf16 top-2 near-tie, where merged (delta rounded into
# bf16 weights) and multiplexed (delta added in f32) can legitimately
# break the tie differently. 16 tokens x 4 streams is a real smoke on
# top of tests/test_lora_serving.py, which pins exactness through
# chunked prefill, cache hits, and speculative rounds.
LORA_EXACT_NEW = 16


def _lora_drain(q: "queue.Queue[object]") -> List[int]:
    toks: List[int] = []
    while True:
        t = q.get(timeout=600)
        if t is None:
            break
        if isinstance(t, BaseException):
            raise t
        toks.append(int(t))
    return toks


def _timed_batch(engine: ServingEngine, jobs, serial: bool = False,
                 new_tokens: int = LORA_NEW) -> float:
    """Aggregate tok/s for a list of (prompt, adapter) jobs, either
    submitted concurrently (one batch) or drained one at a time."""
    t0 = time.perf_counter()
    if serial:
        total = sum(
            len(_lora_drain(engine.submit(p, new_tokens, adapter=a)))
            for p, a in jobs
        )
    else:
        qs = [engine.submit(p, new_tokens, adapter=a) for p, a in jobs]
        total = sum(len(_lora_drain(q)) for q in qs)
    return total / (time.perf_counter() - t0)


def run_lora_arm(out: Dict) -> None:
    """Multi-tenant LoRA multiplexing, three claims: (1) a mixed-adapter
    batch decodes every tenant's tokens exactly as that tenant's
    merge_lora'd dedicated engine would at temperature 0; (2) batching
    the tenants together buys the usual continuous-batching
    consolidation over serving the same requests one at a time; (3) a
    LoRA-enabled engine with an *empty* pool prices the adapter_id=-1
    fast path against the plain pre-LoRA engine (the lax.cond skip —
    non-LoRA traffic must not pay for the feature existing)."""
    from dstack_tpu.workloads.generate import generate
    from dstack_tpu.workloads.lora import merge_lora
    from dstack_tpu.workloads.lora_serving import demo_adapter

    config = PRESETS["tiny"]
    params = init_params(config, jax.random.PRNGKey(0))
    adapters = {
        name: demo_adapter(config, params, jax.random.PRNGKey(seed),
                           rank=LORA_RANK, targets=("wq", "wv"))
        for name, seed in zip(LORA_TENANTS, (3, 5, 7))
    }
    engine = ServingEngine(config, params, slots=8, max_len=256,
                           kv_block_size=16, lora_max_adapters=4,
                           lora_rank=LORA_RANK, lora_targets=("wq", "wv"))
    try:
        for name, tree in adapters.items():
            engine.load_adapter(name, tree)
        tenants = list(LORA_TENANTS) + [None]

        # Exactness: one mixed batch, every adapter plus the base model
        # concurrently; each stream must equal its own merged reference.
        # (Prompt seeds sit away from bf16 argmax near-ties: merge_lora
        # rounds the delta into the bf16 weights while the pool adds it
        # in f32, so a top-2 gap inside bf16 rounding can flip either
        # way without any engine bug.)
        prompts = {a: _bench_prompt(900 + i, PROMPT_LEN)
                   for i, a in enumerate(tenants)}
        qs = {a: engine.submit(prompts[a], LORA_EXACT_NEW, adapter=a)
              for a in tenants}
        got = {a: _lora_drain(qs[a]) for a in tenants}
        exact = {}
        for a in tenants:
            ref_params = params if a is None else merge_lora(
                params, adapters[a], rank=LORA_RANK, alpha=16.0)
            ref = generate(config, ref_params,
                           jnp.asarray([prompts[a]], dtype=jnp.int32),
                           max_new_tokens=LORA_EXACT_NEW, temperature=0.0)
            exact[a or "base"] = got[a] == [int(t) for t in ref[0]]
        assert all(exact.values()), f"mixed batch diverged: {exact}"

        # Consolidation: same four tenants, concurrent vs one at a time,
        # alternating reps (host-load drift), distinct prompt seeds per
        # phase so the prefix cache never subsidizes the timing.
        reps, seed = 3, 1000
        mixed, serial = [], []
        for _ in range(reps):
            jobs = [(_bench_prompt(seed + i, PROMPT_LEN), a)
                    for i, a in enumerate(tenants)]
            seed += len(tenants)
            mixed.append(_timed_batch(engine, jobs))
            jobs = [(_bench_prompt(seed + i, PROMPT_LEN), a)
                    for i, a in enumerate(tenants)]
            seed += len(tenants)
            serial.append(_timed_batch(engine, jobs, serial=True))
        adapters_loaded = engine.stats()["adapters_loaded"]
    finally:
        engine.close()

    # Empty-pool overhead: nothing loaded, 8 base streams x 128 tokens,
    # vs the plain engine on identical traffic. Longer and more repeated
    # than the phases above: the claim is a ~1.0 ratio (the two engines
    # now dispatch byte-identical programs when no adapter is in
    # flight), and sub-second samples on a shared core swing +-10% —
    # long samples + alternating order + medians converge on the truth.
    def _jobs(s):
        return [(_bench_prompt(s + i, PROMPT_LEN), None) for i in range(8)]

    plain = ServingEngine(config, params, slots=8, max_len=256,
                          kv_block_size=16)
    empty = ServingEngine(config, params, slots=8, max_len=256,
                          kv_block_size=16, lora_max_adapters=4,
                          lora_rank=LORA_RANK, lora_targets=("wq", "wv"))
    overhead_reps = 6
    try:
        _timed_batch(plain, _jobs(2000))  # warm the jits
        _timed_batch(empty, _jobs(2100))
        seed = 2200
        p_tok, e_tok = [], []
        for r in range(overhead_reps):
            # Swap measurement order every rep: host speed decays
            # monotonically over the phase on a shared core, so a fixed
            # plain-then-empty order taxes whichever engine always runs
            # second with a systematic ~5-10% deficit.
            pair = [(plain, p_tok), (empty, e_tok)]
            if r % 2:
                pair.reverse()
            for eng, acc in pair:
                acc.append(_timed_batch(eng, _jobs(seed), new_tokens=128))
                seed += 8
    finally:
        plain.close()
        empty.close()

    med = statistics.median
    s = {
        "arm": "lora_multiplex", "model": "tiny", "slots": 8,
        "tenants": len(LORA_TENANTS), "rank": LORA_RANK,
        "targets": ["wq", "wv"], "adapters_loaded": adapters_loaded,
        "prompt_len": PROMPT_LEN, "new_tokens": LORA_NEW, "reps": reps,
        "exact_new_tokens": LORA_EXACT_NEW,
        "mixed_batch_token_exact": all(exact.values()),
        "mixed_tok_s": round(med(mixed), 1),
        "serial_tok_s": round(med(serial), 1),
        "consolidation_x": round(med(mixed) / med(serial), 2),
        "overhead_reps": overhead_reps,
        "plain_tok_s": round(med(p_tok), 1),
        "empty_pool_tok_s": round(med(e_tok), 1),
        "empty_pool_vs_plain": round(med(e_tok) / med(p_tok), 3),
    }
    out["scenarios"].append(s)
    print(json.dumps(s), flush=True)


def run_recorder_overhead_arm(out: Dict) -> None:
    """Prices the r15 flight recorder on the decode hot path: identical
    8-stream x 128-token traffic on a recorder-off engine (trace_ring=0
    — begin() returns before touching a slot) vs a recorder-on engine at
    the deployment shape (256-slot ring + 50 ms tail capture, so every
    request also pays the tail-store check at finish). The recorder
    preallocates its ring and marks phases by appending to a preallocated
    slot's list, so the claim is <2% on both tok/s and TTFT p95; same
    alternating-order + medians discipline as the empty-pool arm (the
    effect being priced is smaller than shared-core drift)."""
    config = PRESETS["tiny"]
    params = init_params(config, jax.random.PRNGKey(0))
    streams, new_tokens = 8, 128

    def _phase(eng, seed: int) -> Dict:
        prompts = [_bench_prompt(seed + i, PROMPT_LEN) for i in range(streams)]
        results: List[Dict] = [None] * streams  # type: ignore
        t0 = time.perf_counter()

        def worker(i: int) -> None:
            t = time.perf_counter()
            results[i] = _drain_timed(
                eng.submit(prompts[i], max_new_tokens=new_tokens),
                t, new_tokens,
            )

        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(streams)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        ttfts = sorted(r["ttft"] for r in results)
        return {"tok_s": streams * new_tokens / wall,
                "ttft_p95_ms": _pct(ttfts, 0.95)}

    rec_off = ServingEngine(config, params, slots=8, max_len=256,
                            kv_block_size=16, trace_ring=0)
    rec_on = ServingEngine(config, params, slots=8, max_len=256,
                           kv_block_size=16, trace_ring=256,
                           trace_slow_ms=50.0)
    # Single smoke runs measured the pair at +2.8% and -4.1% — the
    # recorder's true cost sits well under one run's shared-core noise,
    # so the arm leans on rep count: 10 alternating pairs and medians.
    reps = 10
    try:
        _phase(rec_off, 4000)  # warm the jits
        _phase(rec_on, 4100)
        seed = 4200
        offs, ons = [], []
        for r in range(reps):
            pair = [(rec_off, offs), (rec_on, ons)]
            if r % 2:
                pair.reverse()
            for eng, acc in pair:
                acc.append(_phase(eng, seed))
                seed += streams
        trace_stats = rec_on.stats()["trace"]
    finally:
        rec_off.close()
        rec_on.close()

    med = statistics.median
    on_tok = med(p["tok_s"] for p in ons)
    off_tok = med(p["tok_s"] for p in offs)
    s = {
        "arm": "recorder_overhead", "model": "tiny", "slots": 8,
        "streams": streams, "prompt_len": PROMPT_LEN,
        "new_tokens": new_tokens, "reps": reps,
        "trace_ring": 256, "trace_slow_ms": 50.0,
        "recorder_off_tok_s": round(off_tok, 1),
        "recorder_on_tok_s": round(on_tok, 1),
        "on_vs_off": round(on_tok / off_tok, 4),
        "overhead_pct": round((1.0 - on_tok / off_tok) * 100, 2),
        "recorder_off_ttft_p95_ms": round(
            med(p["ttft_p95_ms"] for p in offs), 1),
        "recorder_on_ttft_p95_ms": round(
            med(p["ttft_p95_ms"] for p in ons), 1),
        "traces_recorded": trace_stats["started_total"],
        "tail_captured": trace_stats["tail_captured_total"],
    }
    # Every recorder-on request must actually have been traced — a 0%
    # overhead number for a recorder that silently no-oped is not a
    # measurement. (+1 warmup phase, x8 streams each.)
    assert s["traces_recorded"] >= (reps + 1) * streams, s["traces_recorded"]
    out["scenarios"].append(s)
    print(json.dumps(s), flush=True)


NN_STEADY = ("tenant-a", "tenant-b", "tenant-c")
NN_REQS = 6            # requests per steady tenant per phase
NN_NEW = 32
NN_FLOOD_THREADS = 8   # flood keeps this many requests in flight


def run_noisy_neighbor_arm(out: Dict) -> None:
    """Per-tenant QoS under a flooding tenant. Three phases on one
    engine: no flood (baseline), flood with no gate (the failure mode:
    the flood's long prefills occupy every slot and steady TTFT
    inflates), and flood behind a QoSGate — the flooder exceeds its
    token bucket ~10x and is mostly shed, so steady tenants' TTFT p95
    stays near the no-flood baseline. TTFT is measured from when the
    tenant WANTED to submit (before QoS admission), so nothing the gate
    does is hidden from the number."""
    from dstack_tpu.dataplane.qos import QoSGate, TenantShedError

    config = PRESETS["tiny"]
    params = init_params(config, jax.random.PRNGKey(0))
    engine = ServingEngine(config, params, slots=8, max_len=256,
                           kv_block_size=16)

    def _phase(gate, flood: bool, seed0: int) -> Dict:
        stop = threading.Event()
        lock = threading.Lock()
        counts = {"shed": 0, "flood_completed": 0}
        ttfts: List[float] = []

        def _flooder(tix: int) -> None:
            k = 0
            while not stop.is_set():
                frid = seed0 + 7919 * (tix + 1) + k
                k += 1
                if gate is not None:
                    try:
                        gate.admit("flood", timeout=0.1)
                    except TenantShedError:
                        with lock:
                            counts["shed"] += 1
                        time.sleep(0.02)  # hostile: ignores Retry-After
                        continue
                try:
                    q = engine.submit(_bench_prompt(frid, FLOOD_PROMPT), 2)
                    while q.get(timeout=600) is not None:
                        pass
                    with lock:
                        counts["flood_completed"] += 1
                finally:
                    if gate is not None:
                        gate.release()

        def _steady(tname: str, tix: int) -> None:
            for k in range(NN_REQS):
                t_want = time.perf_counter()
                if gate is not None:
                    while True:
                        try:
                            gate.admit(tname)
                            break
                        except TenantShedError as e:
                            time.sleep(min(e.retry_after, 0.2))
                try:
                    q = engine.submit(
                        _bench_prompt(seed0 + 100 * tix + k, PROMPT_LEN),
                        NN_NEW)
                    first = q.get(timeout=600)
                    if isinstance(first, BaseException):
                        raise first
                    t_first = time.perf_counter()
                    while q.get(timeout=600) is not None:
                        pass
                finally:
                    if gate is not None:
                        gate.release()
                with lock:
                    ttfts.append((t_first - t_want) * 1e3)

        flooders = []
        if flood:
            flooders = [threading.Thread(target=_flooder, args=(t,),
                                         daemon=True)
                        for t in range(NN_FLOOD_THREADS)]
            for t in flooders:
                t.start()
            time.sleep(0.5)  # let the flood occupy the engine first
        steadies = [threading.Thread(target=_steady, args=(n, i))
                    for i, n in enumerate(NN_STEADY)]
        for t in steadies:
            t.start()
        for t in steadies:
            t.join()
        stop.set()
        for t in flooders:
            t.join(timeout=600)
        return {"ttft_p95_ms": round(_pct(sorted(ttfts), 0.95), 1),
                **counts}

    # Steady tenants send NN_REQS back-to-back: burst covers them, the
    # flood's demand (NN_FLOOD_THREADS spinning submitters) is >10x its
    # 1/s refill, so nearly all of it sheds.
    def _gate():
        return QoSGate(rate=1.0, burst=float(NN_REQS), concurrency=8)

    reps = 5
    try:
        _phase(None, flood=False, seed0=1)  # warm the jits
        base, qoff, qon = [], [], []
        for rep in range(reps):
            base.append(_phase(None, False, seed0=30000 + 3000 * rep))
            qoff.append(_phase(None, True, seed0=31000 + 3000 * rep))
            qon.append(_phase(_gate(), True, seed0=32000 + 3000 * rep))
    finally:
        engine.close()

    def med(phases):
        return statistics.median(p["ttft_p95_ms"] for p in phases)

    s = {
        "arm": "noisy_neighbor", "model": "tiny", "slots": 8,
        "steady_tenants": len(NN_STEADY), "steady_reqs": NN_REQS,
        "prompt_len": PROMPT_LEN, "new_tokens": NN_NEW,
        "flood_threads": NN_FLOOD_THREADS,
        "flood_prompt_len": FLOOD_PROMPT, "reps": reps,
        "qos": {"rate": 1.0, "burst": float(NN_REQS), "concurrency": 8},
        "no_flood_ttft_p95_ms": med(base),
        "flood_qos_off_ttft_p95_ms": med(qoff),
        "flood_qos_on_ttft_p95_ms": med(qon),
        "qos_off_vs_no_flood": round(med(qoff) / med(base), 3),
        "qos_on_vs_no_flood": round(med(qon) / med(base), 3),
        "flood_shed_total": sum(p["shed"] for p in qon),
        "flood_completed_qos_on": sum(p["flood_completed"] for p in qon),
        "flood_completed_qos_off": sum(p["flood_completed"] for p in qoff),
    }
    out["scenarios"].append(s)
    print(json.dumps(s), flush=True)


def run_overcommit_arm(out: Dict) -> None:
    """Hierarchical KV cache (r16): host-RAM spill tier + slot
    preemption under residency overcommit. Two engines share one tiny
    device pool shape; the overcommit engine adds a host tier and a
    `max_resident_slots` cap at 1/4 of its slot count:

    - admission: the overcommit engine accepts STREAMS concurrent
      shared-prefix streams — 4x its HBM-resident cap — and completes
      all of them; the baseline holds the same resident capacity as its
      total capacity.
    - prefix-hit rate held: between waves, unique-prompt churn floods
      the pool so LRU evicts the shared prefix. The baseline drops it
      (the next wave's first stream cold-re-prefills); the overcommit
      engine spills it to host RAM and the next lookup swaps it back,
      so the hit rate holds at 1.0.
    - swap-in beats re-prefill: the post-churn probe's TTFT is the
      bench column — host-hit swap-in + suffix-only prefill vs the
      baseline's full-prompt recompute — alongside the engine-side
      kv_swap_in histogram mean and a controlled slot preempt/resume
      (engine.preempt mid-decode, drain to completion) timing the
      wholesale chain swap-in against the cold prefill of the same
      prompt shape."""
    config = PRESETS["tiny"]
    params = init_params(config, jax.random.PRNGKey(0))
    resident = 2
    streams = 4 * resident  # the 4x overcommit admission claim
    prefix_len, suffix_len, new_tok = 64, 16, 32
    block, pool = 8, 48  # pool holds ~2 resident chains, not the churn

    def _mk(host: bool) -> ServingEngine:
        kw = dict(max_len=160, kv_block_size=block, kv_pool_blocks=pool,
                  prefill_chunk_tokens=32)
        if host:
            return ServingEngine(config, params, slots=streams,
                                 max_resident_slots=resident,
                                 kv_host_budget_bytes=256 << 20, **kw)
        return ServingEngine(config, params, slots=resident, **kw)

    def _run_one(engine, p, n=new_tok):
        t = time.perf_counter()
        return _drain_timed(engine.submit(p, max_new_tokens=n), t, n)

    def _phase(engine, seed0: int) -> Dict:
        prefix = [((seed0 * 101 + j * 31) % TOKEN_MOD) + 1
                  for j in range(prefix_len)]

        def suffix(i):
            return [((seed0 + i * 7 + j * 3) % TOKEN_MOD) + 1
                    for j in range(suffix_len)]

        # Cold pass fills the prefix cache; its prefill cost is the
        # re-prefill column's denominator.
        s0 = engine.stats()
        cold = _run_one(engine, prefix + suffix(0))
        s_cold = engine.stats()
        cold_prefill_ms = (s_cold["prefill_seconds_sum"]
                           - s0["prefill_seconds_sum"]) * 1e3

        # Churn: unique prompts whose cached chains overflow the pool,
        # LRU-evicting the shared prefix (spilled host-side when the
        # tier exists, dropped otherwise).
        for c in range(8):
            _run_one(engine, [((seed0 + 977 * (c + 1) + j * 13) % TOKEN_MOD)
                              + 1 for j in range(prefix_len)], 4)

        # Post-churn probe: fresh suffix, so only the prefix can hit.
        # TTFT is the swap-in-vs-re-prefill bench column.
        s1 = engine.stats()
        probe = _run_one(engine, prefix + suffix(99))
        s2 = engine.stats()

        # Concurrent wave: `streams` shared-prefix streams at once —
        # 4x the overcommit engine's resident cap.
        results = [None] * streams
        threads = [
            threading.Thread(
                target=lambda i=i: results.__setitem__(
                    i, _run_one(engine, prefix + suffix(1 + i))
                )
            )
            for i in range(streams)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        s3 = engine.stats()

        def d(key, a, b):
            return b[key] - a[key]

        lookups = (d("prefix_cache_hits_total", s1, s3)
                   + d("prefix_cache_misses_total", s1, s3))
        ttfts = sorted(r["ttft"] for r in results)
        return {
            "cold_prefill_ms": cold_prefill_ms,
            "cold_ttft_ms": cold["ttft"],
            "probe_ttft_ms": probe["ttft"],
            "probe_prefill_tokens": d("prefill_tokens_computed_total",
                                      s1, s2),
            "probe_host_hits": d("prefix_cache_host_hits_total", s1, s2),
            "wave_agg_tok_s": streams * new_tok / wall,
            "wave_ttft_p50_ms": _pct(ttfts, 0.50),
            "wave_ttft_p95_ms": _pct(ttfts, 0.95),
            "hit_rate": ((d("prefix_cache_hits_total", s1, s3) / lookups)
                         if lookups else 0.0),
            "device_hits": d("prefix_cache_device_hits_total", s1, s3),
            "host_hits": d("prefix_cache_host_hits_total", s1, s3),
            "prefill_tokens": d("prefill_tokens_computed_total", s1, s3),
            "spills": d("kv_spills_total", s1, s3),
            "admitted": d("admitted_total", s2, s3),
        }

    def _preempt_resume(engine, seed0: int) -> Dict:
        """Controlled slot preemption: park a mid-decode stream's whole
        chain host-side, let it readmit, drain to completion. The
        swap_in histogram diff times the wholesale chain restore."""
        h0 = engine.stats()["swap_in_hist"]
        p = [((seed0 * 17 + j * 5) % TOKEN_MOD) + 1
             for j in range(prefix_len + suffix_len)]
        q = engine.submit(p, max_new_tokens=new_tok)
        got = 0
        while got < 2:  # live mid-decode before asking for the swap
            item = q.get(timeout=600)
            if isinstance(item, BaseException):
                raise item
            got += 1
        engine.preempt(q)
        while True:
            item = q.get(timeout=600)
            if item is None:
                break
            if isinstance(item, BaseException):
                raise item
            got += 1
        assert got == new_tok, got
        h1 = engine.stats()["swap_in_hist"]
        n = h1["count"] - h0["count"]
        return {"swap_ins": n,
                "swap_in_ms": ((h1["sum"] - h0["sum"]) / n * 1e3)
                if n else 0.0}

    reps = 3
    base_phases, over_phases, swaps = [], [], []
    engine = _mk(host=False)
    try:
        _phase(engine, seed0=5)  # warm the jits
        for rep in range(reps):
            base_phases.append(_phase(engine, seed0=40000 + 999 * rep))
    finally:
        engine.close()
    engine = _mk(host=True)
    try:
        _phase(engine, seed0=5)
        for rep in range(reps):
            over_phases.append(_phase(engine, seed0=50000 + 999 * rep))
            swaps.append(_preempt_resume(engine, seed0=60000 + 999 * rep))
    finally:
        engine.close()

    def med(phases, key):
        return statistics.median(p[key] for p in phases)

    over_stats = {k: round(med(over_phases, k), 3)
                  for k in ("hit_rate", "probe_ttft_ms", "cold_prefill_ms",
                            "wave_agg_tok_s", "wave_ttft_p50_ms",
                            "wave_ttft_p95_ms")}
    base_stats = {k: round(med(base_phases, k), 3)
                  for k in ("hit_rate", "probe_ttft_ms", "cold_prefill_ms",
                            "wave_agg_tok_s", "wave_ttft_p50_ms",
                            "wave_ttft_p95_ms")}
    swap_in_ms = statistics.median(s["swap_in_ms"] for s in swaps)
    s = {
        "arm": "overcommit", "model": "tiny",
        "prefix_len": prefix_len, "suffix_len": suffix_len,
        "new_tokens": new_tok, "kv_pool_blocks": pool,
        "kv_block_size": block, "reps": reps,
        "streams": streams,
        "max_resident_slots": resident,
        "overcommit_ratio": round(streams / resident, 1),
        "wave_admitted": sum(p["admitted"] for p in over_phases) // reps,
        "baseline": {**base_stats, "slots": resident,
                     "probe_prefill_tokens":
                         int(med(base_phases, "probe_prefill_tokens"))},
        "overcommit": {
            **over_stats, "slots": streams,
            "probe_prefill_tokens":
                int(med(over_phases, "probe_prefill_tokens")),
            "probe_host_hits": int(med(over_phases, "probe_host_hits")),
            "host_hits_total": sum(p["host_hits"] for p in over_phases),
            "spills_total": sum(p["spills"] for p in over_phases),
        },
        # The acceptance columns: hit rate held under churn only on the
        # tiered engine, and resuming from host RAM (prefix swap-back on
        # the probe; wholesale chain swap-in on the preempted slot)
        # undercuts recomputing the prompt.
        "hit_rate_held": round(med(over_phases, "hit_rate")
                               - med(base_phases, "hit_rate"), 3),
        "probe_ttft_vs_cold_reprefill": round(
            med(over_phases, "probe_ttft_ms")
            / max(1e-9, med(base_phases, "probe_ttft_ms")), 3),
        "slot_swap_in_ms": round(swap_in_ms, 2),
        "slot_swap_in_vs_cold_prefill": round(
            swap_in_ms / max(1e-9, med(over_phases, "cold_prefill_ms")), 3),
    }
    out["scenarios"].append(s)
    print(json.dumps(s), flush=True)


NAMED_ARMS = {
    "sharded": run_sharded_arm,
    "disagg": run_disagg_arm,
    "lora": run_lora_arm,
    "noisy_neighbor": run_noisy_neighbor_arm,
    "overcommit": run_overcommit_arm,
    "recorder": run_recorder_overhead_arm,
}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="BENCH_serving_r16.json")
    ap.add_argument("--arms", default="",
                    help="comma-separated named arms to run alone"
                         f" ({', '.join(sorted(NAMED_ARMS))}); default"
                         " runs the full suite")
    cli = ap.parse_args()
    on_tpu = jax.devices()[0].platform != "cpu"
    config = PRESETS["smol-1b"].with_(n_layers=8) if on_tpu else PRESETS["tiny"]
    stream_counts = (1, 8, 16, 32) if on_tpu else (1, 4)
    global TOKEN_MOD
    TOKEN_MOD = min(TOKEN_MOD, config.vocab_size - 2)

    params = init_params(config, jax.random.PRNGKey(0))
    from dstack_tpu.workloads.quant import quantize_params

    out = {
        "model": "smol-1b/8L" if on_tpu else "tiny",
        "prompt_len": PROMPT_LEN,
        "new_tokens": NEW_TOKENS,
        "slots": SLOTS,
        "max_prefills_per_chunk": 4,  # engine default; the fairness knob
        "device": jax.devices()[0].device_kind,
        # Context for reading the numbers: this dev chip sits behind a
        # tunnel with ~hundreds-of-ms RTT, and the engine pays one host
        # sync per `steps_per_sync` decode steps — so single-stream
        # throughput here is an RTT floor, not a chip limit. The two
        # things this bench pins are exactly the engine's value props:
        # (1) aggregate scales multi-x with streams at fixed sync cost,
        # (2) raising steps_per_sync trades TTFT for throughput.
        "r06_comparison_note": (
            "r12: paged attention attends raggedly over the block"
            " tables (workloads/paged_attention.py) — no consumer"
            " gathers a dense per-slot view anymore, so the per-chunk"
            " gather tax r08 noted and the r10 view cache built to"
            " amortize it are both gone; batch-1 cells should sit"
            " within 5% of the dense r06 engine at every sync cadence,"
            " while the paged pool keeps the KV-footprint win"
            " (kv_budget_stretch)"
        ),
        "scenarios": [],
    }
    arm_filter = [a.strip() for a in cli.arms.split(",") if a.strip()]
    if arm_filter:
        unknown = sorted(set(arm_filter) - set(NAMED_ARMS))
        if unknown:
            raise SystemExit(f"unknown arms: {unknown}"
                             f" (known: {sorted(NAMED_ARMS)})")
        for name in arm_filter:
            NAMED_ARMS[name](out)
        with open(cli.out, "w") as f:
            json.dump(out, f, indent=1)
        return
    variants = [("bf16", params, 4), ("bf16", params, 32),
                ("int8", quantize_params(params), 32)]
    for dtype, p, sps in variants:
        engine = ServingEngine(
            config, p, slots=SLOTS, max_len=MAX_LEN, steps_per_sync=sps
        )
        if "hbm_headroom_bytes" not in out:
            # The dense scratch the ragged rewrite deleted: r10's decode
            # carried gathered k and v views of (layers, slots, max_len,
            # KV, hd) across chunks. That allocation no longer exists
            # anywhere in the engine, so it is headroom the KV budget
            # can absorb as extra pool blocks — kv_budget_stretch is the
            # pool-growth factor the same HBM footprint now affords.
            row = 2 * config.n_kv_heads * config.head_dim  # k + v
            out["hbm_headroom_bytes"] = (
                config.n_layers * SLOTS * MAX_LEN * row
                * jnp.dtype(config.activation_dtype).itemsize
            )
            out["kv_budget_stretch"] = round(
                (engine._pool_bytes_target + out["hbm_headroom_bytes"])
                / engine._pool_bytes_target, 3
            )
        try:
            # Warmup twice: the first pass compiles the full-prompt chunk
            # bucket and the decode program; the SECOND hits the prefix
            # cache the first left behind and compiles the suffix-sized
            # chunk bucket — the program every cache-hit admission below
            # actually runs (one cold pass would leave a 1s+ XLA compile
            # inside the measured 1-stream TTFT).
            run_scenario(engine, 1)
            run_scenario(engine, 1)
            for n in stream_counts:
                # Single-stream runs are short (~1.5 s) and land within
                # scheduler-noise of each other run-to-run; take the
                # median of 3 by aggregate so the r06 comparison tracks
                # the engine, not one GC pause.
                reps = 3 if n == 1 else 1
                runs = sorted(
                    (run_scenario(engine, n) for _ in range(reps)),
                    key=lambda r: r["agg_tok_s"],
                )
                s = {"dtype": dtype, "steps_per_sync": sps,
                     **runs[len(runs) // 2]}
                out["scenarios"].append(s)
                print(json.dumps(s), flush=True)
        finally:
            engine.close()

    # SLO scenario: 2x slot oversubscription under BOUNDED admission.
    # r4 measured the unbounded version at ttft_p50 = 10.8 s for +7%
    # aggregate; here the waiting backlog is capped at half the slots —
    # the 2x burst fills all slots immediately (admission counts free
    # slots), ~half the overflow queues, the rest sheds with Retry-After
    # and re-enters as slots turn over. Accepted requests keep a bounded
    # TTFT.
    slo_streams = SLOTS * 2
    engine = ServingEngine(
        config, params, slots=SLOTS, max_len=MAX_LEN, steps_per_sync=32,
        max_pending=SLOTS // 2,
    )
    try:
        run_scenario(engine, 1)
        s = {"dtype": "bf16", "steps_per_sync": 32, "admission": "bounded",
             **run_scenario(engine, slo_streams, retry=True)}
        out["scenarios"].append(s)
        print(json.dumps(s), flush=True)
    finally:
        engine.close()

    # Prefill-heavy: long prompts, short generations — the shape that
    # made the r05 sequential admission serialize ~16 prefills in front
    # of every decode chunk. With overlap, prefill host work hides
    # behind the decode chunk; the scenario's util split shows how much
    # decode time admission still costs.
    pf_prompt = min(256, MAX_LEN - 32) if on_tpu else 16
    pf_new = 16 if on_tpu else 4
    pf_streams = SLOTS * 2 if on_tpu else 4
    engine = ServingEngine(
        config, params, slots=SLOTS, max_len=MAX_LEN, steps_per_sync=32,
    )
    try:
        run_scenario(engine, 1, prompt_len=pf_prompt, new_tokens=pf_new)
        s = {"dtype": "bf16", "steps_per_sync": 32, "shape": "prefill_heavy",
             **run_scenario(engine, pf_streams, prompt_len=pf_prompt,
                            new_tokens=pf_new)}
        out["scenarios"].append(s)
        print(json.dumps(s), flush=True)
    finally:
        engine.close()

    # Shared-system-prompt scenarios (r08, paged KV + prefix cache).
    # The prefix is the ISSUE's 512-token system prompt on hardware; on
    # CPU the tiny preset's 256-token context forces a scaled-down
    # shape — the accounting claims (compute drop, budget stretch) are
    # ratios and survive the scaling, absolute tok/s does not.
    sp_prefix = 512 if on_tpu else 48
    sp_suffix = 32 if on_tpu else 8
    sp_new = 32 if on_tpu else 16
    sp_max_len = 1024 if on_tpu else 128
    engine = ServingEngine(
        config, params, slots=SLOTS, max_len=sp_max_len, steps_per_sync=4,
        # The scenario IS an 8-wide burst: let one boundary admit all of
        # it (the suffix chunks are 8 tokens each — well under the
        # chunk budget), so TTFT p95 measures the cache, not the
        # admission window.
        max_prefills_per_chunk=8,
    )
    try:
        s = {"dtype": "bf16", "steps_per_sync": 4,
             **run_warmed_burst_scenario(engine, 8, sp_prefix, sp_suffix,
                                         sp_new)}
        out["scenarios"].append(s)
        print(json.dumps(s), flush=True)
    finally:
        engine.close()
    engine = ServingEngine(
        config, params, slots=SLOTS, max_len=sp_max_len, steps_per_sync=4,
    )
    try:
        s = {"dtype": "bf16", "steps_per_sync": 4,
             **run_shared_prefix_scenario(engine, 8, sp_prefix, sp_suffix,
                                          sp_new)}
        out["scenarios"].append(s)
        print(json.dumps(s), flush=True)
    finally:
        engine.close()

    # Speculative decoding (r10): each drafter arm runs against a plain
    # baseline engine at the SAME steps_per_sync=1 cadence, so the tok/s
    # ratio isolates speculation (draft scan + wide verify vs one step
    # per token) from sync-batching effects. The int8 drafter is the
    # deployment default (quantized copy of the target: high acceptance,
    # ~half the weight reads); the random-init drafter is the worst
    # case the adaptive draft length + whole-batch fallback must bound.
    # These arms use a latency-oriented engine shape — slots sized to
    # the stream counts, window sized to the request — not the big
    # throughput engine above: speculation's win is per-token overhead
    # (dispatch, per-step sync) amortized k+1 times per target forward,
    # and padding every step out to 16 idle slots x 512-token views
    # buries exactly that effect under dead-slot compute.
    spec_streams = (1, 8) if on_tpu else (1, 4)
    spec_slots = max(spec_streams)
    spec_max_len = 224  # prompt 64 + 128 new + slack, block-aligned
    baseline = {}
    engine = ServingEngine(
        config, params, slots=spec_slots, max_len=spec_max_len,
        steps_per_sync=1,
    )
    try:
        run_scenario(engine, 1)
        run_scenario(engine, 1)
        for n in spec_streams:
            reps = 3 if n == 1 else 1
            runs = sorted((run_scenario(engine, n) for _ in range(reps)),
                          key=lambda r: r["agg_tok_s"])
            s = {"dtype": "bf16", "steps_per_sync": 1, "arm": "no_spec",
                 "slots": spec_slots, "max_len": spec_max_len,
                 **runs[len(runs) // 2]}
            baseline[n] = s["agg_tok_s"]
            out["scenarios"].append(s)
            print(json.dumps(s), flush=True)
    finally:
        engine.close()
    drafters = [
        ("spec_int8_drafter", quantize_params(params)),
        ("spec_adversarial_drafter", init_params(config, jax.random.PRNGKey(9))),
    ]
    for arm, drafter in drafters:
        engine = ServingEngine(
            config, params, slots=spec_slots, max_len=spec_max_len,
            steps_per_sync=1, spec_enable=True, spec_max_draft=4,
            spec_draft_params=drafter, spec_draft_config=config,
        )
        try:
            run_scenario(engine, 1)
            run_scenario(engine, 1)
            for n in spec_streams:
                reps = 3 if n == 1 else 1
                runs = sorted(
                    (run_spec_scenario(engine, n) for _ in range(reps)),
                    key=lambda r: r["agg_tok_s"],
                )
                s = {"dtype": "bf16", "steps_per_sync": 1, "arm": arm,
                     "slots": spec_slots, "max_len": spec_max_len,
                     **runs[len(runs) // 2]}
                s["tok_s_vs_no_spec"] = round(
                    s["agg_tok_s"] / baseline[n], 3
                )
                out["scenarios"].append(s)
                print(json.dumps(s), flush=True)
        finally:
            engine.close()

    # Both drafters are the TARGET's shape, so on a compute-bound CPU a
    # draft step costs about a target step and speculation's wall-clock
    # ceiling is (accepted+1)/(k+1) < 1 no matter how cheap attention
    # gets — the ragged rewrite removed the per-step gather both
    # programs paid (r10 int8 arm: 44 tok/s absolute; r12: ~6x that) but
    # cannot change that arithmetic. tok_s_vs_no_spec > 1 for the int8
    # arm is a claim about the memory-bound TPU regime, where int8
    # halves the drafter's weight reads per step. The adversarial arm
    # clears 1 on CPU because its collapsed acceptance EWMA drives the
    # engine into whole-batch fallback (plain decode) almost every
    # round.
    out["spec_note"] = (
        "CPU ceiling: equal-shape drafter => draft step ~= target step,"
        " so tok_s_vs_no_spec <= (accepted+1)/(spec_max_draft+1) < 1 on"
        " a compute-bound host; the int8 arm's >1 target is a TPU"
        " (memory-bound, int8 = half the weight reads) claim. Compare"
        " absolute agg_tok_s vs r10 for the ragged-attention effect on"
        " the spec programs themselves"
    )

    # --- r13 arms: sharded bit-exactness/overhead + disagg isolation.
    # CPU-only: the sharded arm needs a controlled virtual device count
    # (subprocess XLA_FLAGS) and the disagg arm's nice()-based prefill
    # deprioritization models the split on a single shared core; on a
    # real TPU both claims belong to multi-chip / multi-host runs.
    # --- r14 arms: multi-tenant LoRA multiplexing (merged-engine token
    # equality + consolidation + empty-pool overhead) and the
    # noisy-neighbor QoS phases. Also CPU-only: both are correctness /
    # isolation claims whose interference mechanics live in the host
    # loop, not the chip.
    # --- r15 arm: flight-recorder overhead — the <2% claim for leaving
    # per-request tracing on in production. CPU-only like the others:
    # the recorder's cost is host-side Python on the engine loop, which
    # is exactly what a CPU run isolates.
    # --- r16 arm: hierarchical KV overcommit — host-RAM spill tier +
    # slot preemption at 4x residency overcommit. CPU-only too: the
    # tier's mechanics (LRU spill, swap-back, preempt/readmit) are
    # host-loop code, and the swap-in-vs-re-prefill ratio it pins is a
    # bytes-moved-vs-forward-pass comparison that holds per platform.
    if not on_tpu:
        run_sharded_arm(out)
        run_disagg_arm(out)
        run_lora_arm(out)
        run_noisy_neighbor_arm(out)
        run_overcommit_arm(out)
        run_recorder_overhead_arm(out)

    agg = {s["streams"]: s["agg_tok_s"] for s in out["scenarios"]
           if s.get("dtype") == "bf16" and s.get("steps_per_sync") == 4
           and "shape" not in s}
    if len(agg) > 1:
        out["batching_speedup"] = round(max(agg.values()) / agg[1], 2)
        print(f"# continuous batching: {out['batching_speedup']}x aggregate"
              f" over batch-1 ({max(agg.values()):.0f} vs {agg[1]:.0f} tok/s)",
              flush=True)
    # r10's batch-1 steps_per_sync=4 cell collapsed (28.1 tok/s vs dense
    # r06's 84.7): the cross-chunk view cache invalidated at every chunk
    # boundary, so the highest sync cadence re-gathered the whole dense
    # view 32x per 128 tokens. r12 attends raggedly over the tables —
    # there is no view to gather or invalidate — so that cell should
    # recover to the steps_per_sync=32 number. Absolute tok/s is not
    # comparable across sessions on a shared-CPU container (host load
    # shifts every cell), so quantify with the WITHIN-RUN sps4/sps32
    # ratio: sps4 runs 8x more chunk boundaries per token, and the
    # per-boundary cost is exactly what separated the two cells in r10.
    note = ("r10's cross-chunk view cache invalidated at every chunk"
            " boundary, so batch-1 steps_per_sync=4 re-gathered the"
            " dense view 32x per 128 tokens (28.1 tok/s vs dense r06's"
            " 84.7); r12 attends raggedly over the block tables and"
            " deletes the view cache outright")

    def _cell(art, sps):
        return next(
            s["agg_tok_s"] for s in art["scenarios"]
            if s.get("dtype") == "bf16" and s.get("steps_per_sync") == sps
            and s.get("streams") == 1 and "shape" not in s
            and "arm" not in s
        )
    try:
        with open("BENCH_serving_r10.json") as f:
            r10 = json.load(f)
        r10_ratio = _cell(r10, 4) / _cell(r10, 32)
        r12_ratio = _cell(out, 4) / _cell(out, 32)
        note += (f"; 1-stream bf16 sps4/sps32 ratio (machine-speed"
                 f" invariant): r12 {r12_ratio:.3f} vs r10 {r10_ratio:.3f}"
                 f" — the per-boundary gather cost"
                 f" {'is gone' if r12_ratio > r10_ratio else 'did not close'}"
                 f" (absolute cells: r12 {_cell(out, 4)} tok/s vs r10"
                 f" {_cell(r10, 4)}, but cross-session absolutes on a"
                 " shared-CPU container track host load, not the code)")
        with open("BENCH_serving_r06.json") as f:
            r06 = json.load(f)
        note += (f"; the dense r06 engine's same-run ratio was"
                 f" {_cell(r06, 4) / _cell(r06, 32):.3f}")
    except (OSError, StopIteration, KeyError, json.JSONDecodeError):
        pass
    out["r10_comparison_note"] = note
    with open(cli.out, "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
