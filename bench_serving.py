"""Serving-engine throughput benchmark on the real chip.

The continuous-batching engine (workloads/serving.py) exists to multiplex
many decode streams over one chip; its batch-1 numbers (519 tok/s int8 /
416 bf16, round 3) only proved correctness overhead. This measures the
reason it exists: aggregate tokens/s and tail latency at 1/8/16/32
concurrent streams, bf16 vs int8 weight-only quantization.

Metrics per scenario:
- agg_tok_s    — total generated tokens / wall time (the capacity number)
- ttft_p50/p95 — submit -> first token, ms (includes prefill + queueing;
  on a tunneled dev chip this carries the tunnel RTT)
- tpt_p50/p95  — per-stream EFFECTIVE token cadence, ms: (last_token_ts -
  first_token_ts) / (n-1) for each stream, percentiles across streams.
  Tokens arrive in steps_per_sync-sized bursts, so raw inter-token
  deltas are mostly ~0 and their percentiles said nothing (the r4 file
  published tpt_p50=0.0); the per-stream cadence is the number a client
  actually experiences.

Each scenario also records the engine's own view of the run: the TTFT
breakdown (queue wait -> prefill -> first chunk, from the scheduler's
EWMA gauges) and the decode/prefill/idle utilization split — the numbers
that show whether prefill is stealing decode time (the r05 failure mode:
agg tok/s flat 675.8 -> 669.2 going 16 -> 32 streams while TTFT p95 hit
4.6 s, classic prefill head-of-line blocking, fixed by the overlapped
scheduler).

The admission-control scenario exercises shedding: slots oversubscribed
2x with `max_pending` bounded — overflow is rejected with a Retry-After
hint and the client retries; TTFT of ACCEPTED requests stays bounded
instead of the 10.8 s p50 measured unbounded in r4. The prefill-heavy
scenario (long prompts, short generations) isolates prefill/decode
overlap: sequential admission serializes the long prefills in front of
every decode chunk, overlap hides them behind it.

Writes BENCH_serving_r06.json and prints one JSON line per scenario.
Regression guard: tests/test_serving.py pins engine==one-shot decode
numerics; this file pins the performance claim (continuous batching must
show a multi-x aggregate over batch-1, and TTFT p95 at 32 streams must
stay bounded while agg tok/s holds the 16-stream plateau).
"""

import json
import queue
import statistics
import threading
import time
from typing import Dict, List

import jax

from dstack_tpu.workloads.config import PRESETS
from dstack_tpu.workloads.serving import ServingEngine
from dstack_tpu.workloads.transformer import init_params

PROMPT_LEN = 64
NEW_TOKENS = 128
MAX_LEN = 512
SLOTS = 16  # engine batch width; streams beyond this queue


def _drain_timed(q: "queue.Queue[object]", t0: float, n_expected: int) -> Dict:
    ts: List[float] = []
    while True:
        item = q.get(timeout=600)
        if item is None:
            break
        if isinstance(item, BaseException):
            raise item
        ts.append(time.perf_counter())
    assert len(ts) == n_expected, len(ts)
    # Effective per-token cadence for THIS stream: tokens land in
    # steps_per_sync bursts, so per-delta percentiles are ~0/meaningless;
    # span/(n-1) is the cadence a client sees.
    cadence = (ts[-1] - ts[0]) / (len(ts) - 1) * 1e3 if len(ts) > 1 else 0.0
    return {"ttft": (ts[0] - t0) * 1e3, "cadence": cadence, "n": len(ts)}


def _pct(xs, p):
    return xs[min(len(xs) - 1, int(p * len(xs)))]


def run_scenario(engine: ServingEngine, streams: int, retry: bool = False,
                 prompt_len: int = None, new_tokens: int = None) -> Dict:
    from dstack_tpu.workloads.serving import EngineOverloadedError

    prompt_len = PROMPT_LEN if prompt_len is None else prompt_len
    new_tokens = NEW_TOKENS if new_tokens is None else new_tokens
    prompts = [
        [((i * 37 + j * 13) % 30000) + 1 for j in range(prompt_len)]
        for i in range(streams)
    ]
    results: List[Dict] = [None] * streams  # type: ignore
    retries = [0] * streams
    stats0 = engine.stats()  # counter snapshot: per-scenario util diffs
    t0 = time.perf_counter()

    def worker(i: int) -> None:
        while True:
            # TTFT is measured from the submit that was ACCEPTED: with
            # admission control the client's total latency is visible in
            # `retries` + Retry-After, while TTFT shows the bounded
            # in-engine latency SLO.
            t_submit = time.perf_counter()
            try:
                q = engine.submit(prompts[i], max_new_tokens=new_tokens)
            except EngineOverloadedError as e:
                if not retry:
                    raise
                retries[i] += 1
                time.sleep(e.retry_after)
                continue
            results[i] = _drain_timed(q, t_submit, new_tokens)
            return

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(streams)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    ttfts = sorted(r["ttft"] for r in results)
    cadences = sorted(r["cadence"] for r in results)
    total = sum(r["n"] for r in results)

    # The engine's own breakdown of the TTFT it just served, from the
    # summary counters diffed across the scenario (exact per-scenario
    # means — the EWMA gauges carry compile-spike history from warmup):
    # queue wait (submit -> admission), prefill (admission -> first
    # token, which under the overlapped scheduler includes the decode
    # chunk it hid behind), and the residual of the measured client-side
    # p50. Plus the decode/prefill/idle wall-time split — the gauges
    # that pin "prefill never stalls decode" on hardware-free CI where
    # absolute tok/s means nothing.
    stats = engine.stats()
    n_adm = max(1, stats["admitted_total"] - stats0["admitted_total"])
    queue_ms = (
        stats["queue_wait_seconds_sum"] - stats0["queue_wait_seconds_sum"]
    ) / n_adm * 1e3
    prefill_ms = (
        stats["prefill_seconds_sum"] - stats0["prefill_seconds_sum"]
    ) / n_adm * 1e3
    ttft_p50 = _pct(ttfts, 0.50)
    spans = {
        k: stats[f"{k}_seconds_total"] - stats0[f"{k}_seconds_total"]
        for k in ("decode", "prefill", "idle")
    }
    span_total = sum(spans.values()) or 1.0
    out = {
        "streams": streams,
        "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "agg_tok_s": round(total / wall, 1),
        "ttft_p50_ms": round(ttft_p50, 1),
        "ttft_p95_ms": round(_pct(ttfts, 0.95), 1),
        "tpt_p50_ms": round(_pct(cadences, 0.50), 2),
        "tpt_p95_ms": round(_pct(cadences, 0.95), 2),
        "wall_s": round(wall, 2),
        "ttft_breakdown_ms": {
            "queue_wait": round(queue_ms, 1),
            "prefill": round(prefill_ms, 1),
            "first_chunk_residual": round(
                max(0.0, ttft_p50 - queue_ms - prefill_ms), 1
            ),
        },
        "util": {k: round(v / span_total, 4) for k, v in spans.items()},
    }
    if retry:
        out["sheds"] = sum(retries)
        out["max_pending"] = engine.max_pending
    return out


def main() -> None:
    on_tpu = jax.devices()[0].platform != "cpu"
    config = PRESETS["smol-1b"].with_(n_layers=8) if on_tpu else PRESETS["tiny"]
    stream_counts = (1, 8, 16, 32) if on_tpu else (1, 4)

    params = init_params(config, jax.random.PRNGKey(0))
    from dstack_tpu.workloads.quant import quantize_params

    out = {
        "model": "smol-1b/8L" if on_tpu else "tiny",
        "prompt_len": PROMPT_LEN,
        "new_tokens": NEW_TOKENS,
        "slots": SLOTS,
        "max_prefills_per_chunk": 4,  # engine default; the fairness knob
        "device": jax.devices()[0].device_kind,
        # Context for reading the numbers: this dev chip sits behind a
        # tunnel with ~hundreds-of-ms RTT, and the engine pays one host
        # sync per `steps_per_sync` decode steps — so single-stream
        # throughput here is an RTT floor, not a chip limit. The two
        # things this bench pins are exactly the engine's value props:
        # (1) aggregate scales multi-x with streams at fixed sync cost,
        # (2) raising steps_per_sync trades TTFT for throughput.
        "scenarios": [],
    }
    variants = [("bf16", params, 4), ("bf16", params, 32),
                ("int8", quantize_params(params), 32)]
    for dtype, p, sps in variants:
        engine = ServingEngine(
            config, p, slots=SLOTS, max_len=MAX_LEN, steps_per_sync=sps
        )
        try:
            run_scenario(engine, 1)  # warmup: compile prefill/insert/decode
            for n in stream_counts:
                s = {"dtype": dtype, "steps_per_sync": sps,
                     **run_scenario(engine, n)}
                out["scenarios"].append(s)
                print(json.dumps(s), flush=True)
        finally:
            engine.close()

    # SLO scenario: 2x slot oversubscription under BOUNDED admission.
    # r4 measured the unbounded version at ttft_p50 = 10.8 s for +7%
    # aggregate; here the waiting backlog is capped at half the slots —
    # the 2x burst fills all slots immediately (admission counts free
    # slots), ~half the overflow queues, the rest sheds with Retry-After
    # and re-enters as slots turn over. Accepted requests keep a bounded
    # TTFT.
    slo_streams = SLOTS * 2
    engine = ServingEngine(
        config, params, slots=SLOTS, max_len=MAX_LEN, steps_per_sync=32,
        max_pending=SLOTS // 2,
    )
    try:
        run_scenario(engine, 1)
        s = {"dtype": "bf16", "steps_per_sync": 32, "admission": "bounded",
             **run_scenario(engine, slo_streams, retry=True)}
        out["scenarios"].append(s)
        print(json.dumps(s), flush=True)
    finally:
        engine.close()

    # Prefill-heavy: long prompts, short generations — the shape that
    # made the r05 sequential admission serialize ~16 prefills in front
    # of every decode chunk. With overlap, prefill host work hides
    # behind the decode chunk; the scenario's util split shows how much
    # decode time admission still costs.
    pf_prompt = min(256, MAX_LEN - 32) if on_tpu else 16
    pf_new = 16 if on_tpu else 4
    pf_streams = SLOTS * 2 if on_tpu else 4
    engine = ServingEngine(
        config, params, slots=SLOTS, max_len=MAX_LEN, steps_per_sync=32,
    )
    try:
        run_scenario(engine, 1, prompt_len=pf_prompt, new_tokens=pf_new)
        s = {"dtype": "bf16", "steps_per_sync": 32, "shape": "prefill_heavy",
             **run_scenario(engine, pf_streams, prompt_len=pf_prompt,
                            new_tokens=pf_new)}
        out["scenarios"].append(s)
        print(json.dumps(s), flush=True)
    finally:
        engine.close()

    agg = {s["streams"]: s["agg_tok_s"] for s in out["scenarios"]
           if s["dtype"] == "bf16" and s["steps_per_sync"] == 4}
    if len(agg) > 1:
        out["batching_speedup"] = round(max(agg.values()) / agg[1], 2)
        print(f"# continuous batching: {out['batching_speedup']}x aggregate"
              f" over batch-1 ({max(agg.values()):.0f} vs {agg[1]:.0f} tok/s)",
              flush=True)
    with open("BENCH_serving_r06.json", "w") as f:
        json.dump(out, f, indent=1)


if __name__ == "__main__":
    main()
