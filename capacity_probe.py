"""Capacity probe: concurrent runs through the FSM, 1..N replicas.

The reference documents its per-replica capacity as "150 active jobs /
runs / instances at <= 2 min processing latency" (reference
background/__init__.py:40-46). This probe submits concurrent runs on
the local backend over a real socket — every run provisions a (local)
instance, handshakes a real runner process, executes, and terminates —
and records the submit->done latency distribution plus aggregate
throughput.

With `--replicas "1,2,4"` it sweeps replica counts: each arm gets a
fresh file-backed DB shared by one in-process server (the API endpoint)
plus N-1 real subprocess replicas, all running the full background FSM
with hash-sharded ownership (services/shard_map.py). The per-arm
`throughput_runs_per_min` is the aggregate scaling story.

A shortfall (failed or unfinished runs) no longer aborts the probe:
every arm's JSON is emitted with `failed` / `unfinished` counts and the
process exits nonzero, so CI gets both the data and the red light.

Run: python capacity_probe.py [--runs 200] [--replicas 1,2,4]
     [--out CAPACITY_r11.json]
"""

import argparse
import json
import os
import statistics
import subprocess
import sys
import tempfile
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path

from latency_probe import ProbeServer

REPO_ROOT = str(Path(__file__).resolve().parent)

_REPLICA_WORKER = """
import asyncio, json, sys

from dstack_tpu.server.app import create_app
from dstack_tpu.server.http import Server


async def main():
    db_path, runner_bin = sys.argv[1:3]
    app = create_app(db_path=db_path, run_background_tasks=True)
    server = Server(app, "127.0.0.1", 0)
    await server.start()
    ctx = app.state["ctx"]
    ctx.overrides["local_backend_config"] = {"runner_binary": runner_bin}
    print(json.dumps({"event": "up", "port": server.port,
                      "replica": ctx.replica_id}), flush=True)
    await asyncio.sleep(100000)  # killed by the parent


asyncio.run(main())
"""


def _req(url, token, body):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(), method="POST",
        headers={"Authorization": f"Bearer {token}",
                 "Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=60) as resp:
        return json.loads(resp.read() or b"{}")


def _build_runner() -> str:
    # Agents are the NATIVE C++ runner: a capacity probe measures the
    # control plane driving N agents, and python-runner processes would
    # bill ~1 s of interpreter startup CPU per run to the orchestrator
    # (decisive on small probe machines — this box exposes 1 core).
    native = Path(__file__).parent / "agents" / "native"
    runner_path = native / "build" / "dstack-tpu-runner"
    try:
        subprocess.run(["cmake", "-B", "build", "-G", "Ninja",
                        "-DCMAKE_BUILD_TYPE=Release"], cwd=native, check=True,
                       capture_output=True)
        subprocess.run(["cmake", "--build", "build"], cwd=native, check=True,
                       capture_output=True)
    except FileNotFoundError:
        # No cmake on this box: a stale binary still beats no probe, and a
        # direct g++ build of the runner target works (plain C++17).
        if not runner_path.exists():
            runner_path.parent.mkdir(exist_ok=True)
            subprocess.run(
                ["g++", "-O2", "-std=c++17", "-pthread", "-o", str(runner_path),
                 "runner/main.cc", "runner/executor.cc", "runner/cluster_env.cc",
                 "runner/repo.cc", "common/http.cc", "common/util.cc",
                 "common/tpu_telemetry.cc", "-lutil"],
                cwd=native, check=True, capture_output=True,
            )
    return str(runner_path)


def _spawn_replica(i: int, db_path: str, runner_bin: str, script: str,
                   ttl: float, tmp: str):
    errlog = open(Path(tmp) / f"probe-replica-{i}.stderr", "wb")
    proc = subprocess.Popen(
        [sys.executable, script, db_path, runner_bin],
        stdout=subprocess.PIPE, stderr=errlog,
        env={
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": REPO_ROOT,
            "DSTACK_TPU_MULTI_REPLICA": "1",
            "DSTACK_TPU_REPLICA_ID": f"probe-replica-{i}",
            "DSTACK_TPU_LEASE_TTL": str(ttl),
        },
    )
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            raise RuntimeError(f"probe replica {i} died before 'up'")
        try:
            msg = json.loads(line)
        except ValueError:
            continue
        if msg.get("event") == "up":
            return proc
    raise RuntimeError(f"probe replica {i} never came up")


def _run_arm(n_replicas: int, runs: int, timeout: float, runner_bin: str,
             pg_dsn, tmp: str) -> dict:
    """One probe arm: fresh DB, 1 in-process + N-1 subprocess replicas."""
    from dstack_tpu.server import settings

    ttl = 15.0
    # File-backed DB: the deployment shape (sqlite WAL + reader pool);
    # :memory: cannot use pooled readers (each connection is its own DB)
    # and cannot be shared with subprocess replicas at all. With
    # DSTACK_TPU_TEST_PG_DSN set, a single-replica arm instead measures
    # the Postgres engine (pgwire pool) end to end.
    db_file = tempfile.NamedTemporaryFile(
        suffix=".db", dir=tmp, delete=False)
    db_path = pg_dsn if (pg_dsn and n_replicas == 1) else db_file.name

    # The in-process server is replica 1 and the API endpoint; flipping
    # the module flag makes its ClaimLocker distributed and its ShardMap
    # active (subprocess replicas get the same via env).
    settings.MULTI_REPLICA = n_replicas > 1
    os.environ["DSTACK_TPU_LEASE_TTL"] = str(ttl)
    srv = ProbeServer(
        polling=False, db_path=db_path,
        backend_config={"runner_binary": runner_bin},
    ).start()
    workers = []
    script = str(Path(tmp) / "probe_replica.py")
    Path(script).write_text(_REPLICA_WORKER)
    try:
        for i in range(n_replicas - 1):
            workers.append(
                _spawn_replica(i, db_path, runner_bin, script, ttl, tmp))

        base = f"{srv.url}/api/project/main/runs"
        t0 = time.perf_counter()
        submitted_at = {}

        def submit(i: int) -> None:
            name = f"cap-{i:03d}"
            _req(f"{base}/submit", srv.token, {"run_spec": {
                "run_name": name,
                "configuration": {
                    "type": "task", "commands": ["true"],
                    "resources": {"cpu": "1..", "memory": "0.1.."},
                },
                "ssh_key_pub": "ssh-rsa PROBE",
            }})
            submitted_at[name] = time.perf_counter() - t0

        with ThreadPoolExecutor(max_workers=32) as pool:
            list(pool.map(submit, range(runs)))
        submit_window = time.perf_counter() - t0

        # Poll run state straight off the DB, not via /runs/list: the
        # probe measures the FSM, and list-serializing N runs with job
        # submissions every poll would bill O(runs^2) of pydantic CPU to
        # the control plane on a 1-core box. (Postgres arms keep the API
        # poll: the pgwire DSN is not a sqlite file.)
        import sqlite3 as _sqlite3

        poll_db = None
        if db_path == db_file.name:
            poll_db = _sqlite3.connect(f"file:{db_path}?mode=ro", uri=True)

        def _statuses():
            if poll_db is not None:
                return poll_db.execute(
                    "SELECT run_name, status FROM runs WHERE deleted = 0"
                ).fetchall()
            return [
                ((r.get("run_spec") or {}).get("run_name"), r["status"])
                for r in _req(f"{base}/list", srv.token, {"limit": runs + 10})
            ]

        done_at = {}
        deadline = t0 + timeout
        last_report = 0.0
        while time.perf_counter() < deadline and len(done_at) < runs:
            now = time.perf_counter() - t0
            counts = {}
            for name, status in _statuses():
                if name not in submitted_at:
                    continue
                counts[status] = counts.get(status, 0) + 1
                if name not in done_at and status in (
                        "done", "failed", "terminated"):
                    done_at[name] = (now, status)
            if now - last_report > 10:
                print(f"# replicas={n_replicas} t={now:.0f}s {counts}",
                      file=sys.stderr, flush=True)
                last_report = now
            time.sleep(0.25)
        if poll_db is not None:
            poll_db.close()

        finished = dict(done_at)
        failures = [n for n, (_, s) in finished.items() if s != "done"]
        out = {
            "replicas": n_replicas,
            "runs": runs,
            "engine": "postgres" if db_path == pg_dsn and pg_dsn else "sqlite",
            "failed": len(failures),
            "unfinished": runs - len(finished),
            "submit_window_s": round(submit_window, 1),
        }
        if finished:
            lat = sorted(finished[n][0] - submitted_at[n] for n in finished)

            def pct(p):
                return round(lat[min(len(lat) - 1, int(p * len(lat)))], 1)

            buckets = {}
            for v in lat:
                key = f"{int(v // 15) * 15}-{int(v // 15) * 15 + 15}s"
                buckets[key] = buckets.get(key, 0) + 1
            all_done = max(v[0] for v in finished.values())
            out.update({
                "all_done_s": round(all_done, 1),
                "throughput_runs_per_min": round(
                    len(finished) / all_done * 60, 1),
                "done_latency_s": {
                    "p50": pct(0.50), "p90": pct(0.90), "p95": pct(0.95),
                    "max": round(lat[-1], 1),
                    "mean": round(statistics.mean(lat), 1),
                },
                "histogram": dict(sorted(
                    buckets.items(), key=lambda kv: int(kv[0].split("-")[0])
                )),
            })
        else:
            out.update({"all_done_s": None, "throughput_runs_per_min": 0.0})
        return out
    finally:
        for proc in workers:
            proc.kill()
        for proc in workers:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        srv.stop()


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--runs", type=int, default=200,
                        help="runs per probe arm")
    parser.add_argument("--replicas", default="1",
                        help="comma-separated replica counts, e.g. 1,2,4")
    parser.add_argument("--out", default="CAPACITY_r11.json")
    parser.add_argument("--timeout", type=float, default=600.0)
    args = parser.parse_args()

    arm_sizes = [int(s) for s in args.replicas.split(",") if s.strip()]
    pg_dsn = os.getenv("DSTACK_TPU_TEST_PG_DSN")
    runner_bin = _build_runner()

    arms = []
    with tempfile.TemporaryDirectory(prefix="dstack-capacity-") as tmp:
        for n in arm_sizes:
            arms.append(
                _run_arm(n, args.runs, args.timeout, runner_bin, pg_dsn, tmp))

    out = {
        "arms": arms,
        # Replica scaling is a CPU story: N replicas are N full server
        # processes, so aggregate throughput can only scale up to the
        # core count of the probe host. Record it so an inverted curve
        # on a small box reads as what it is.
        "host_cpus": os.cpu_count(),
        "reference_capacity": "150 active jobs/runs/instances per replica"
                              " @ <=2min processing latency"
                              " (ref background/__init__.py:40-46)",
    }
    if os.cpu_count() and os.cpu_count() < max(arm_sizes, default=1):
        out["note"] = (
            f"host exposes {os.cpu_count()} CPU(s) for {max(arm_sizes)}"
            " replica processes: arms beyond the core count measure"
            " correctness under contention, not scaling"
        )
    print(json.dumps(out, indent=1))
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)

    # The red light: data above, nonzero exit here — never an abort that
    # swallows the numbers.
    shortfall = [a for a in arms if a["failed"] or a["unfinished"]]
    if shortfall:
        print(f"# SHORTFALL in {len(shortfall)} arm(s):"
              f" {[(a['replicas'], a['failed'], a['unfinished']) for a in shortfall]}",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
