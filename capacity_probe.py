"""Single-replica capacity probe: 200 concurrent runs through the FSM.

The reference documents its per-replica capacity as "150 active jobs /
runs / instances at <= 2 min processing latency" (reference
background/__init__.py:40-46). This probe submits 200 concurrent runs on
the local backend over a real socket — every run provisions a (local)
instance, handshakes a real runner process, executes, and terminates —
and records the submit->done latency distribution, i.e. pure control-
plane processing latency under 1.33x the reference's rated load.

Emits ONE JSON document (CAPACITY_r04.json via --out).

Run: python capacity_probe.py [--runs 200] [--out CAPACITY_r04.json]
"""

import argparse
import json
import os
import statistics
import time
import urllib.request
from concurrent.futures import ThreadPoolExecutor

from latency_probe import ProbeServer


def _req(url, token, body):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(), method="POST",
        headers={"Authorization": f"Bearer {token}",
                 "Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=60) as resp:
        return json.loads(resp.read() or b"{}")


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--runs", type=int, default=200)
    parser.add_argument("--out", default="CAPACITY_r04.json")
    parser.add_argument("--timeout", type=float, default=600.0)
    args = parser.parse_args()

    import subprocess
    import tempfile
    from pathlib import Path

    # File-backed DB: the deployment shape (sqlite WAL + reader pool);
    # :memory: cannot use pooled readers (each connection is its own DB).
    # With DSTACK_TPU_TEST_PG_DSN set, the probe instead measures the
    # Postgres engine (pgwire pool) end to end.
    pg_dsn = os.getenv("DSTACK_TPU_TEST_PG_DSN")
    db_file = tempfile.NamedTemporaryFile(suffix=".db", delete=False)
    # Agents are the NATIVE C++ runner: a capacity probe measures the
    # control plane driving N agents, and python-runner processes would
    # bill ~1 s of interpreter startup CPU per run to the orchestrator
    # (decisive on small probe machines — this box exposes 1 core).
    native = Path(__file__).parent / "agents" / "native"
    runner_path = native / "build" / "dstack-tpu-runner"
    try:
        subprocess.run(["cmake", "-B", "build", "-G", "Ninja",
                        "-DCMAKE_BUILD_TYPE=Release"], cwd=native, check=True,
                       capture_output=True)
        subprocess.run(["cmake", "--build", "build"], cwd=native, check=True,
                       capture_output=True)
    except FileNotFoundError:
        # No cmake on this box: a stale binary still beats no probe, and a
        # direct g++ build of the runner target works (plain C++17).
        if not runner_path.exists():
            runner_path.parent.mkdir(exist_ok=True)
            subprocess.run(
                ["g++", "-O2", "-std=c++17", "-pthread", "-o", str(runner_path),
                 "runner/main.cc", "runner/executor.cc", "runner/cluster_env.cc",
                 "runner/repo.cc", "common/http.cc", "common/util.cc",
                 "common/tpu_telemetry.cc", "-lutil"],
                cwd=native, check=True, capture_output=True,
            )
    runner_bin = str(runner_path)
    srv = ProbeServer(
        polling=False, db_path=pg_dsn or db_file.name,
        backend_config={"runner_binary": runner_bin},
    ).start()
    try:
        base = f"{srv.url}/api/project/main/runs"
        t0 = time.perf_counter()
        submitted_at = {}

        def submit(i: int) -> None:
            name = f"cap-{i:03d}"
            _req(f"{base}/submit", srv.token, {"run_spec": {
                "run_name": name,
                "configuration": {
                    "type": "task", "commands": ["true"],
                    "resources": {"cpu": "1..", "memory": "0.1.."},
                },
                "ssh_key_pub": "ssh-rsa PROBE",
            }})
            submitted_at[name] = time.perf_counter() - t0

        with ThreadPoolExecutor(max_workers=32) as pool:
            list(pool.map(submit, range(args.runs)))
        submit_window = time.perf_counter() - t0

        done_at = {}
        deadline = t0 + args.timeout
        last_report = 0.0
        while time.perf_counter() < deadline and len(done_at) < args.runs:
            now = time.perf_counter() - t0
            counts = {}
            for r in _req(f"{base}/list", srv.token, {"limit": args.runs + 10}):
                name = (r.get("run_spec") or {}).get("run_name")
                if name not in submitted_at:
                    continue
                counts[r["status"]] = counts.get(r["status"], 0) + 1
                if name not in done_at and r["status"] in ("done", "failed", "terminated"):
                    done_at[name] = (now, r["status"])
            if now - last_report > 10:
                print(f"# t={now:.0f}s {counts}", file=__import__('sys').stderr, flush=True)
                last_report = now
            time.sleep(0.5)

        finished = {n: v for n, v in done_at.items()}
        assert len(finished) == args.runs, (
            f"only {len(finished)}/{args.runs} finished in {args.timeout}s"
        )
        failures = [n for n, (_, s) in finished.items() if s != "done"]
        lat = sorted(finished[n][0] - submitted_at[n] for n in finished)

        def pct(p):
            return round(lat[min(len(lat) - 1, int(p * len(lat)))], 1)

        buckets = {}
        for v in lat:
            key = f"{int(v // 15) * 15}-{int(v // 15) * 15 + 15}s"
            buckets[key] = buckets.get(key, 0) + 1
        out = {
            "runs": args.runs,
            "engine": "postgres" if pg_dsn else "sqlite",
            "failed": len(failures),
            "submit_window_s": round(submit_window, 1),
            "all_done_s": round(max(v[0] for v in finished.values()), 1),
            "throughput_runs_per_min": round(
                args.runs / max(v[0] for v in finished.values()) * 60, 1
            ),
            "done_latency_s": {
                "p50": pct(0.50), "p90": pct(0.90), "p95": pct(0.95),
                "max": round(lat[-1], 1), "mean": round(statistics.mean(lat), 1),
            },
            "histogram": dict(sorted(
                buckets.items(), key=lambda kv: int(kv[0].split("-")[0])
            )),
            "reference_capacity": "150 active jobs/runs/instances per replica"
                                  " @ <=2min processing latency"
                                  " (ref background/__init__.py:40-46)",
        }
        print(json.dumps(out, indent=1))
        with open(args.out, "w") as f:
            json.dump(out, f, indent=1)
    finally:
        srv.stop()


if __name__ == "__main__":
    main()
