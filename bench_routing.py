"""Prefix-affinity fleet routing benchmark: cache-aware replica selection
vs plain least-outstanding, measured on REAL serving engines.

An N-replica fleet of native model servers (tiny preset, real prefix
caches) sits behind one real `python -m dstack_tpu.dataplane` worker.
Each arm runs twice — affinity routing on (the shipped default) and off
(`DSTACK_TPU_ROUTING_AFFINITY=0`, the pre-PR-18 least-outstanding
policy) — and reads cluster prefill compute straight off the engines'
`prefill_tokens_computed_total` counters, so the headline number is
device work actually avoided, not a proxy-side estimate.

Arms:

1. shared_prefix — G prompt groups sharing a long fixed prefix with
   fixed-width unique tails. Least-outstanding smears every group over
   all replicas (each replica re-prefills each prefix); affinity pins a
   group to the replica that already holds its blocks.
2. multi_session — S chat sessions, each with a fixed persona block and
   fixed-width per-turn questions. Same shape as production multi-turn
   traffic: per-session reuse only pays on the replica that served the
   session before.
3. adapter_skew — 2 replicas each preloading a different LoRA adapter,
   traffic split across `base:adapter` ids. Affinity routes to the
   adapter-resident replica; the baseline misroutes ~half the traffic,
   and every misroute the client must heal with a forced
   `POST /v1/adapters` is counted.
4. cache_cold — unique prompts, zero overlap. Affinity scores all-zero
   and must fall through to the identical least-outstanding path: the
   guardrail arm (TTFT p95 within noise of baseline).

Emits ONE JSON document (BENCH_routing_r18.json via --out) with per-arm
prefill-compute totals, TTFT quantiles, forced-load counts, and a
summary block of speedup ratios + pass/fail booleans (exit nonzero on
regression).

Run: JAX_PLATFORMS=cpu python bench_routing.py [--out BENCH_routing_r18.json]
"""

import argparse
import asyncio
import json
import os
import sys
import time
from pathlib import Path

import httpx

REPO = Path(__file__).resolve().parent
MODEL = "tiny-rt"

# The tiny preset's byte tokenizer keeps the NEWEST `prompt_limit` (248)
# bytes then buckets DOWN to a power of two — so every prompt below 256
# bytes lands in the 128-token bucket, and reuse only exists between
# prompts whose newest-128-byte windows align. All bench prompts are
# therefore exactly PROMPT_LEN bytes: the retained window starts at the
# same offset for every request, shared cores line up block-for-block,
# and the unique 4-byte tail rides in the final (never-hashed) partial
# block so same-group requests share ALL full blocks.
PROMPT_LEN = 300
TAIL = 4


def _prompt(core: str, tail: str) -> str:
    """PROMPT_LEN-byte prompt: `core` repeated, `tail` (TAIL bytes) last.
    Cores carry their group id in every 16-byte window so distinct
    groups share zero chain blocks."""
    body = (core * (PROMPT_LEN // len(core) + 2))[: PROMPT_LEN - TAIL]
    return body + f"{tail:>{TAIL}}"[:TAIL]


# ------------------------------------------------------------ fleet setup


async def _wait_http(url: str, timeout: float = 90.0) -> None:
    deadline = time.perf_counter() + timeout
    async with httpx.AsyncClient(timeout=5.0) as hc:
        while True:
            try:
                r = await hc.get(url)
                if r.status_code == 200:
                    return
            except httpx.HTTPError:
                pass
            if time.perf_counter() > deadline:
                raise RuntimeError(f"{url} never became ready")
            await asyncio.sleep(0.25)


async def _spawn_engine(port: int, adapters=()):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=str(REPO))
    cmd = [
        sys.executable, str(REPO / "examples/deployment/native/server.py"),
        "--preset", "tiny", "--port", str(port), "--model-name", MODEL,
        "--max-new-tokens", "4", "--slots", "8",
        # 16-token prefill chunks: a cold 128-token prompt costs eight
        # chunk steps where a prefix hit's 16-token remainder costs one,
        # so avoided prefill compute shows up as avoided engine STEPS —
        # i.e. as TTFT — even on a host where a single tiny-model matmul
        # is dispatch-overhead-bound.
        "--prefill-chunk-tokens", "16",
    ]
    for name in adapters:
        cmd += ["--adapter", f"{name}=random"]
    if adapters:
        cmd += ["--lora-max-adapters", "4"]
    proc = await asyncio.create_subprocess_exec(
        *cmd, stdout=asyncio.subprocess.DEVNULL,
        stderr=asyncio.subprocess.DEVNULL, env=env,
    )
    return proc


async def _seed_fleet(db_path: str, run_name: str, ports, adapters=()):
    """Migrate a DB and seed one RUNNING service with one replica per
    engine port, model entry included (adapters listed so `base:adapter`
    composite ids resolve through the model route)."""
    from dstack_tpu.models.runs import JobProvisioningData, JobSpec, RunSpec
    from dstack_tpu.server.app import create_app
    from dstack_tpu.server.security import generate_id
    from dstack_tpu.utils.common import utcnow_iso

    app = create_app(
        db_path=db_path, admin_token="bench-admin",
        run_background_tasks=False,
    )
    await app.startup()
    ctx = app.state["ctx"]
    project = await ctx.db.fetchone("SELECT * FROM projects WHERE name='main'")
    user = await ctx.db.fetchone("SELECT * FROM users LIMIT 1")
    run_id, now = generate_id(), utcnow_iso()
    spec = RunSpec.model_validate(
        {"run_name": run_name, "repo_id": "local",
         "configuration": {"type": "service", "name": run_name,
                           "port": ports[0], "commands": ["serve"]}}
    )
    model = {"name": MODEL, "format": "openai", "prefix": "/v1"}
    if adapters:
        model["adapters"] = list(adapters)
    await ctx.db.execute(
        "INSERT INTO runs (id, project_id, user_id, run_name, submitted_at,"
        " last_processed_at, status, run_spec, service_spec)"
        " VALUES (?, ?, ?, ?, ?, ?, 'running', ?, ?)",
        (run_id, project["id"], user["id"], run_name, now, now,
         spec.model_dump_json(),
         json.dumps({"url": f"/proxy/services/main/{run_name}/",
                     "model": model})),
    )
    for replica_num, port in enumerate(ports):
        job_spec = JobSpec.model_validate(
            {"job_name": f"{run_name}-0-{replica_num}", "commands": ["serve"],
             "requirements": {"resources": {}},
             "app_specs": [{"app_name": "app", "port": port}]}
        )
        jpd = JobProvisioningData.model_validate(
            {"backend": "local",
             "instance_type": {"name": "local",
                               "resources": {"cpus": 1, "memory_mib": 1024}},
             "instance_id": f"i-{replica_num}", "hostname": "127.0.0.1",
             "internal_ip": "127.0.0.1", "region": "local", "price": 0.0,
             "username": "root", "dockerized": False}
        )
        await ctx.db.execute(
            "INSERT INTO jobs (id, project_id, run_id, run_name, job_num,"
            " replica_num, submitted_at, last_processed_at, status, job_spec,"
            " job_provisioning_data)"
            " VALUES (?, ?, ?, ?, 0, ?, ?, ?, 'running', ?, ?)",
            (generate_id(), project["id"], run_id, run_name, replica_num,
             now, now, job_spec.model_dump_json(), jpd.model_dump_json()),
        )
    await app.shutdown()


async def _spawn_worker(db_path: str, affinity: bool):
    env = dict(
        os.environ, JAX_PLATFORMS="cpu",
        DSTACK_TPU_ROUTING_AFFINITY="1" if affinity else "0",
        DSTACK_TPU_ROUTING_SKETCH_MAX_AGE="30",
    )
    proc = await asyncio.create_subprocess_exec(
        sys.executable, "-m", "dstack_tpu.dataplane",
        "--db", db_path, "--port", "0",
        "--poll-interval", os.environ.get("BENCH_ROUTING_POLL", "1.0"),
        stdout=asyncio.subprocess.PIPE,
        stderr=asyncio.subprocess.DEVNULL, env=env,
    )
    line = await asyncio.wait_for(proc.stdout.readline(), 30)
    port = int(line.decode().rsplit(":", 1)[1])
    await _wait_http(f"http://127.0.0.1:{port}/readyz", 30)
    return proc, port


async def _kill(procs):
    for p in procs:
        if p.returncode is None:
            p.kill()
    for p in procs:
        try:
            await asyncio.wait_for(p.wait(), 10)
        except asyncio.TimeoutError:
            pass


# ------------------------------------------------------------- measurement


async def _engine_counter(hc, port: int, key: str) -> float:
    r = await hc.get(f"http://127.0.0.1:{port}/metrics")
    return float(r.json()[key])


async def _chat_ttft(hc, worker_port: int, body) -> tuple:
    """(status, seconds to first SSE byte) through the worker."""
    t0 = time.perf_counter()
    async with hc.stream(
        "POST", f"http://127.0.0.1:{worker_port}/proxy/models/main/chat/completions",
        json={**body, "stream": True},
    ) as resp:
        if resp.status_code != 200:
            await resp.aread()
            return resp.status_code, None
        async for _ in resp.aiter_raw():
            return 200, time.perf_counter() - t0
    return 200, time.perf_counter() - t0


def _user(content: str):
    return [{"role": "user", "content": content}]


def _arm_requests(arm: str, tag: str):
    """Deterministic request list per arm; `tag` varies content across
    affinity/baseline passes so the second pass never free-rides on KV
    the first pass left behind on shared engines."""
    reqs = []
    if arm == "shared_prefix":
        # 9 prompt families x 8 requests: shared core, unique tail. The
        # family count is COPRIME with the replica count so the
        # baseline's round-robin rotation cannot resonate into
        # accidentally pinning a family to one replica.
        for i in range(72):
            g = i % 9
            core = f"{tag[0]}g{g:02d} docs "
            reqs.append({"model": MODEL,
                         "messages": _user(_prompt(core, f"q{i}"))})
    elif arm == "multi_session":
        # 9 chat sessions x 8 turns, interleaved: fixed persona block
        # per session, the turn number as the only varying content.
        for turn in range(8):
            for s in range(9):
                core = f"{tag[0]}s{s:02d} chat "
                reqs.append({"model": MODEL,
                             "messages": _user(_prompt(core, f"t{turn}"))})
    elif arm == "adapter_skew":
        # Fully unique prompts — this arm isolates adapter residency.
        for i in range(48):
            name = ("fr", "de")[i % 2]
            core = f"{tag[0]}{name}{i:03d} "
            reqs.append({"model": f"{MODEL}:{name}",
                         "messages": _user(_prompt(core, f"a{i}"))})
    elif arm == "cache_cold":
        # Unique request id in every 16-byte window: zero shared blocks.
        for i in range(96):
            core = f"{tag[0]}x{i:03d} "
            reqs.append({"model": MODEL,
                         "messages": _user(_prompt(core, f"c{i}"))})
    return reqs


async def _force_adapter_load(hc, engine_ports, name: str) -> int:
    """The heal a misrouted `base:adapter` request forces on the
    baseline: load the adapter everywhere it is missing. Returns the
    number of loads performed."""
    forced = 0
    for port in engine_ports:
        r = await hc.get(f"http://127.0.0.1:{port}/v1/affinity")
        if name not in r.json().get("adapters", []):
            r = await hc.post(f"http://127.0.0.1:{port}/v1/adapters",
                              json={"name": name, "path": "random"})
            if r.status_code == 200:
                forced += 1
    return forced


async def _run_arm_mode(arm: str, affinity: bool, engine_ports, tmpdir,
                        rep: int = 0) -> dict:
    tag = "aff" if affinity else "base"
    adapters = ("fr", "de") if arm == "adapter_skew" else ()
    db_path = str(Path(tmpdir) / f"{arm}-{tag}{rep}.db")
    await _seed_fleet(db_path, "rt-svc", engine_ports, adapters=adapters)
    worker, wport = await _spawn_worker(db_path, affinity)
    hc = httpx.AsyncClient(timeout=60.0)
    try:
        # Prime routes (and, with affinity on, let one gossip pass land)
        # with a throwaway prompt outside every measured prefix family.
        prime = {"model": MODEL,
                 "messages": _user(_prompt(f"{tag[0]}prime ", "p0"))}
        status, _ = await _chat_ttft(hc, wport, prime)
        assert status == 200, f"prime request failed: {status}"
        # Two poll cycles: the first gossip pass after the route exists
        # is what populates every replica's sketch.
        await asyncio.sleep(2.5 if affinity else 1.0)

        # Unmeasured burn-in shaped like the measured traffic (unique
        # prompts on the no-reuse arms so block-pool eviction churn is
        # warm too, prompt families on the reuse arms). Whichever mode
        # runs first otherwise pays a system-warm-up tax (page cache,
        # scheduler) that the tight cold-arm gate would read as a
        # routing regression.
        burn_sem = asyncio.Semaphore(4)
        burn_family = arm in ("shared_prefix", "multi_session")

        async def burn_one(j):
            core = f"{tag[0]}b{j % 3} " if burn_family else f"{tag[0]}bu{j:03d} "
            async with burn_sem:
                await _chat_ttft(hc, wport, {
                    "model": MODEL,
                    "messages": _user(_prompt(core, f"b{j}")),
                })

        await asyncio.gather(*[burn_one(j) for j in range(24)])
        await asyncio.sleep(0.5)

        before = sum([
            await _engine_counter(hc, p, "prefill_tokens_computed_total")
            for p in engine_ports
        ])
        reqs = _arm_requests(arm, tag)
        ttfts, failures, forced_loads = [], 0, 0

        async def run_wave(wave, conc, stagger):
            sem = asyncio.Semaphore(conc)

            async def one(body, idx):
                nonlocal failures, forced_loads
                await asyncio.sleep(idx * stagger)
                async with sem:
                    status, ttft = await _chat_ttft(hc, wport, body)
                    if status != 200 and ":" in body["model"]:
                        # Misroute to a non-resident replica: heal +
                        # retry, exactly the operator dance affinity
                        # routing exists to avoid.
                        forced_loads += await _force_adapter_load(
                            hc, engine_ports, body["model"].split(":", 1)[1]
                        )
                        status, ttft = await _chat_ttft(hc, wport, body)
                    if status == 200 and ttft is not None:
                        ttfts.append(ttft)
                    else:
                        failures += 1

            await asyncio.gather(*[one(b, i) for i, b in enumerate(wave)])

        if arm in ("shared_prefix", "multi_session"):
            # Plant/harvest: the first request of each prompt family
            # lands first (all cold in BOTH modes — identical work),
            # then one gossip interval passes so every planted family
            # is in the sketches, then the remaining requests run at
            # saturating concurrency. Sketch staleness is bounded by
            # one epoch poll, so without the settle a family's 2nd
            # request would measure cold-start staleness instead of
            # steady-state routing; with it, the harvest wave is free
            # to saturate the fleet — which is where the baseline's
            # re-prefill bill turns into queueing and the TTFT gap
            # affinity exists to close actually shows up.
            await run_wave(reqs[:9], 3, 0.08)
            await asyncio.sleep(1.7)
            await run_wave(reqs[9:], 6, 0.012)
        else:
            # Light fixed-rate load on the control arms: the cold arm's
            # tight 5% gate wants a service-time-bound p95, not a
            # queueing-noise-bound one.
            await run_wave(reqs, {"adapter_skew": 4}.get(arm, 2), 0.012)
        after = sum([
            await _engine_counter(hc, p, "prefill_tokens_computed_total")
            for p in engine_ports
        ])
        ttfts.sort()

        def pct(p):
            return ttfts[min(len(ttfts) - 1, int(p * len(ttfts)))] if ttfts else None

        return {
            "requests": len(reqs),
            "failures": failures,
            "forced_adapter_loads": forced_loads,
            "prefill_tokens_computed": after - before,
            "ttft_p50_ms": round(pct(0.50) * 1000, 2),
            "ttft_p95_ms": round(pct(0.95) * 1000, 2),
        }
    finally:
        await hc.aclose()
        await _kill([worker])


async def _warm_engine(hc, port: int, model: str) -> None:
    """Pay every XLA compile the measured window will need: the cold
    prompt's 16-token prefill chunks (+ decode) first, then the
    16-token hit-remainder path via the same prompt with a different
    tail (112 cached tokens, 16 computed)."""
    core = f"warm{port % 100:02d} "
    for tail in ("w1", "w2"):
        r = await hc.post(
            f"http://127.0.0.1:{port}/v1/chat/completions",
            json={"model": model, "max_tokens": 2,
                  "messages": _user(_prompt(core, tail))},
        )
        assert r.status_code == 200, (port, r.status_code, r.text)


async def _run_arm(arm: str, tmpdir) -> dict:
    # 5 replicas on the prefix-reuse arms: the baseline's spread (and so
    # its re-prefill bill) grows with fleet width, which is exactly the
    # 1/N fleet-hit-rate effect affinity routing removes. 2 replicas
    # isolate adapter residency; 3 keep the cold control arm light.
    n_engines = {"adapter_skew": 2, "cache_cold": 3}.get(arm, 5)
    base_port = {"shared_prefix": 19400, "multi_session": 19430,
                 "adapter_skew": 19460, "cache_cold": 19470}[arm]
    out = {}
    modes = (True, False)
    if os.environ.get("BENCH_ROUTING_BASELINE_FIRST"):
        modes = (False, True)
    # Single-run p95 on a small shared box carries order bias (whichever
    # mode runs first measures a colder system) and one-off scheduler
    # noise, so every TTFT-gated arm runs each mode TWICE on fresh
    # fleets in interleaved order (A B B A — neither mode systematically
    # goes first) and scores each mode by its better p95: a repeat-min
    # estimate of the steady-state tail, applied identically to both
    # modes. The adapter arm's gate is a deterministic forced-load
    # count, so one pass per mode suffices there.
    mode_seq = modes if arm == "adapter_skew" else modes + tuple(reversed(modes))
    reps = {}
    for run_i, affinity in enumerate(mode_seq):
        # Fresh engines per mode run: prefix caches and adapter pools
        # must not leak between passes.
        ports = [base_port + run_i * n_engines + i for i in range(n_engines)]
        per_engine_adapters = (
            [("fr",), ("de",)] if arm == "adapter_skew" else [()] * n_engines
        )
        engines = [
            await _spawn_engine(p, adapters=a)
            for p, a in zip(ports, per_engine_adapters)
        ]
        try:
            await asyncio.gather(*[
                _wait_http(f"http://127.0.0.1:{p}/v1/models") for p in ports
            ])
            async with httpx.AsyncClient(timeout=180.0) as hc:
                for i, p in enumerate(ports):
                    warm_model = (
                        f"{MODEL}:{per_engine_adapters[i][0]}"
                        if per_engine_adapters[i] else MODEL
                    )
                    await _warm_engine(hc, p, warm_model)
            mode = "affinity" if affinity else "baseline"
            res = await _run_arm_mode(arm, affinity, ports, tmpdir,
                                      rep=len(reps.get(mode, [])))
            reps.setdefault(mode, []).append(res)
            print(f"  {arm}/{mode}: {res}", flush=True)
        finally:
            await _kill(engines)
    for mode, runs in reps.items():
        best = min(runs, key=lambda r: r["ttft_p95_ms"])
        if len(runs) > 1:
            best = dict(best)
            best["reps_ttft_p95_ms"] = [r["ttft_p95_ms"] for r in runs]
        out[mode] = best
    return out


def _summary(results: dict) -> dict:
    def ratio(arm, key):
        b = results[arm]["baseline"][key]
        a = results[arm]["affinity"][key]
        return round(b / a, 2) if a else None

    s = {
        "shared_prefix_prefill_drop": ratio("shared_prefix",
                                            "prefill_tokens_computed"),
        "multi_session_prefill_drop": ratio("multi_session",
                                            "prefill_tokens_computed"),
        "shared_prefix_ttft_p95_speedup": ratio("shared_prefix", "ttft_p95_ms"),
        "multi_session_ttft_p95_speedup": ratio("multi_session", "ttft_p95_ms"),
        "adapter_forced_loads_affinity":
            results["adapter_skew"]["affinity"]["forced_adapter_loads"],
        "adapter_forced_loads_baseline":
            results["adapter_skew"]["baseline"]["forced_adapter_loads"],
        "cache_cold_ttft_p95_ratio": round(
            results["cache_cold"]["affinity"]["ttft_p95_ms"]
            / results["cache_cold"]["baseline"]["ttft_p95_ms"], 3),
    }
    s["prefill_drop_at_least_2x"] = (
        (s["shared_prefix_prefill_drop"] or 0) >= 2.0
        and (s["multi_session_prefill_drop"] or 0) >= 2.0
    )
    s["ttft_p95_better_on_affinity_arms"] = (
        (s["shared_prefix_ttft_p95_speedup"] or 0) > 1.0
        and (s["multi_session_ttft_p95_speedup"] or 0) > 1.0
    )
    s["zero_forced_adapter_loads_with_affinity"] = (
        s["adapter_forced_loads_affinity"] == 0
        and results["adapter_skew"]["affinity"]["failures"] == 0
    )
    s["cache_cold_within_5pct"] = s["cache_cold_ttft_p95_ratio"] <= 1.05
    return s


async def _run_all(args) -> dict:
    import tempfile

    results = {}
    arms = args.arms.split(",") if args.arms else [
        "shared_prefix", "multi_session", "adapter_skew", "cache_cold",
    ]
    with tempfile.TemporaryDirectory() as tmpdir:
        for arm in arms:
            print(f"arm: {arm}", flush=True)
            results[arm] = await _run_arm(arm, tmpdir)
    if not args.arms:
        results["summary"] = _summary(results)
    return results


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default="BENCH_routing_r18.json")
    parser.add_argument("--arms", default="",
                        help="comma-separated arm subset (skips summary)")
    args = parser.parse_args()
    results = asyncio.get_event_loop().run_until_complete(_run_all(args))
    with open(args.out, "w") as f:
        json.dump(results, f, indent=2)
        f.write("\n")
    if "summary" not in results:
        raise SystemExit(0)
    print(json.dumps(results["summary"], indent=2))
    ok = (results["summary"]["prefill_drop_at_least_2x"]
          and results["summary"]["ttft_p95_better_on_affinity_arms"]
          and results["summary"]["zero_forced_adapter_loads_with_affinity"]
          and results["summary"]["cache_cold_within_5pct"])
    raise SystemExit(0 if ok else 1)


if __name__ == "__main__":
    main()
