"""Podracer-style RL on dstack-tpu: colocated (Anakin) or split-slice
(Sebulba) actor/learner gangs on the serving engine.

Two modes:

  --mode anakin    One process: actor and learner alternate on the same
                   devices (Podracer's Anakin architecture). Runs
                   anywhere, including CPU — this is the smoke mode.

  --mode sebulba   One process per gang member, role picked by node
                   rank (Podracer's Sebulba architecture):
                     rank 0          learner — consumes trajectory
                                     batches, runs the PPO update,
                                     publishes weights
                     ranks 1..N-1    actors — generate rollouts through
                                     the ServingEngine, pull fresh
                                     weights between rollouts
                   The weight-refresh address comes from
                   DSTACK_TPU_RL_REFRESH_ADDR, which the runner injects
                   into every gang member (parallel/env.py); the
                   trajectory sink listens on the next port up on the
                   same host.

The task toy environment rewards emitting one target token, so the
reward curve visibly climbs within ~10 updates — enough to watch the
full actor -> learner -> weight-refresh loop work end to end. Swap
`TargetTokenEnv` + `tiny_rl_config` for a real env/model to scale up;
every other moving part (epoch-fenced refresh, gang resize, metrics)
stays the same. See docs/guides/rl.md.
"""

import argparse
import json
import os
import time

from dstack_tpu.workloads.rl import (
    Actor,
    Learner,
    RLStats,
    TargetTokenEnv,
    TrajectoryClient,
    TrajectorySink,
    WeightRefreshClient,
    WeightRefreshServer,
    refresh_addr_from_env,
    rl_prometheus_metrics,
    run_anakin,
    tiny_rl_config,
)


def anakin_main(args) -> int:
    out = run_anakin(
        tiny_rl_config(),
        updates=args.updates,
        batch_size=args.batch,
        horizon=args.horizon,
        seed=args.seed,
        refresh="direct",
    )
    print(json.dumps({
        "mode": "anakin",
        "rewards": out["rewards"],
        "env_steps_per_s": round(out["env_steps_per_s"], 2),
        "learn_step_s_mean": round(out["learn_step_s_mean"], 6),
        "final_weight_epoch": out["final_weight_epoch"],
    }, indent=2))
    return 0


def learner_main(args, host: str, port: int) -> int:
    config = tiny_rl_config()
    stats = RLStats()
    gang = max(args.gang_width, 1)
    refresh = WeightRefreshServer(host="0.0.0.0", port=port)
    learner = Learner(
        config, seed=args.seed, learning_rate=2e-2,
        accum_per_actor=1, gang_width=gang, refresh=refresh, stats=stats,
    )
    sink = TrajectorySink("0.0.0.0", port + 1, on_batch=learner.ingest)
    learner.publish()
    try:
        for u in range(args.updates):
            metrics = learner.update_once(timeout=args.timeout)
            learner.publish()
            print(
                f"update {u}: reward={metrics['reward_mean']:.3f} "
                f"loss={metrics['loss']:.4f} epoch={learner.weight_epoch}",
                flush=True,
            )
        print(rl_prometheus_metrics(stats.snapshot()))
    finally:
        sink.close()
        refresh.close()
    return 0


def actor_main(args, host: str, port: int, rank: int) -> int:
    config = tiny_rl_config()
    stats = RLStats()
    env = TargetTokenEnv(config.vocab_size, horizon=args.horizon,
                         seed=args.seed + rank)
    refresh = WeightRefreshClient(host, port)
    # Same epoch-0 init as the learner (same seed), so rollouts before
    # the first refresh already run the learner's policy.
    from dstack_tpu.workloads.train import init_params
    import jax

    params = init_params(config, jax.random.PRNGKey(args.seed))
    actor = Actor(
        config, params, env, actor_id=rank, batch_size=args.batch,
        seed=args.seed + 100 * rank, refresh=refresh, stats=stats,
    )
    traj = TrajectoryClient(host, port + 1)
    try:
        r = 0
        deadline = time.monotonic() + args.timeout
        while time.monotonic() < deadline:
            try:
                actor.maybe_refresh()
                traj.send(actor.rollout(round_ix=r))
            except (ConnectionError, OSError):
                break  # learner finished (or was resized away) — done
            r += 1
    finally:
        traj.close()
        actor.close()
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mode", choices=("anakin", "sebulba"), default="anakin")
    ap.add_argument("--updates", type=int, default=10)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--horizon", type=int, default=8)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--gang-width", type=int,
                    default=int(os.environ.get("DSTACK_NODES_NUM", "2")) - 1,
                    help="actor count the learner folds per update")
    ap.add_argument("--timeout", type=float, default=600.0)
    args = ap.parse_args()

    if args.mode == "anakin":
        return anakin_main(args)

    addr = refresh_addr_from_env()
    if addr is None:
        raise SystemExit(
            "sebulba mode needs DSTACK_TPU_RL_REFRESH_ADDR (set by the "
            "runner for gang runs; export host:port manually for local use)"
        )
    host, port = addr
    rank = int(os.environ.get("DSTACK_NODE_RANK", "0"))
    if rank == 0:
        return learner_main(args, host, port)
    return actor_main(args, host, port, rank)


if __name__ == "__main__":
    raise SystemExit(main())
