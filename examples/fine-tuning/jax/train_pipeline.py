"""Pipeline-parallel fine-tune entrypoint (dp x pp).

The orchestrator injects the JAX coordinator env for multi-host slices;
workloads.pipeline cuts the layer stack into --stages and streams
--microbatches through the ppermute ring schedule.
"""

import argparse
import os

import jax

from dstack_tpu.workloads.config import PRESETS
from dstack_tpu.workloads.pipeline import (
    init_pipeline_state,
    make_pipeline_mesh,
    make_pipeline_train_step,
    pipeline_batch,
)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--preset", default="smol-1b", choices=sorted(PRESETS))
    parser.add_argument("--steps", type=int, default=100)
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--seq-len", type=int, default=2048)
    parser.add_argument("--stages", type=int, default=4)
    parser.add_argument("--microbatches", type=int, default=8)
    args = parser.parse_args()

    if int(os.environ.get("JAX_NUM_PROCESSES", "1")) > 1:
        jax.distributed.initialize()

    config = PRESETS[args.preset]
    n = jax.device_count()
    if n % args.stages:
        raise SystemExit(f"--stages {args.stages} must divide {n} devices")
    mesh = make_pipeline_mesh(jax.devices(), data=n // args.stages, pipe=args.stages)
    state = init_pipeline_state(config, jax.random.PRNGKey(0), mesh=mesh)
    step = make_pipeline_train_step(config, mesh, n_microbatches=args.microbatches)

    dp = mesh.shape["data"]
    per = args.microbatches * dp
    batch_size = ((args.batch_size + per - 1) // per) * per
    batch = pipeline_batch(config, batch_size, args.seq_len, mesh=mesh)

    for i in range(args.steps):
        state, metrics = step(state, batch)
        if i % 10 == 0 or i == args.steps - 1:
            if jax.process_index() == 0:
                print(f"step {i}: loss {float(metrics['loss']):.4f}")
    print("training complete")


if __name__ == "__main__":
    main()
