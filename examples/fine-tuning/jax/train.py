"""Fine-tune a llama-family model with dstack-tpu's workloads library.

Runs unmodified from a single chip to a 32-host v5p-256 pod slice: the
orchestrator injects `JAX_COORDINATOR_ADDRESS` / `JAX_PROCESS_ID` /
`JAX_NUM_PROCESSES` (parallel/env.py), and `jax.distributed.initialize()`
with no arguments consumes exactly those — there is no torchrun/mpirun
equivalent to wire up.

Parity note: the reference's examples/fine-tuning pass MASTER_ADDR +
torchrun flags by hand from DSTACK_* env; here distributed bootstrap is
zero lines of user code.
"""

import argparse
import os

import jax

from dstack_tpu.workloads import checkpoint as ckpt
from dstack_tpu.workloads.config import PRESETS
from dstack_tpu.workloads.sharding import make_mesh
from dstack_tpu.workloads.train import (
    init_train_state,
    make_train_step,
    synthetic_batch,
)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--preset", default="smol-1b", choices=sorted(PRESETS))
    parser.add_argument("--steps", type=int, default=100)
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--seq-len", type=int, default=2048)
    parser.add_argument("--model-parallel", type=int, default=1)
    parser.add_argument("--seq-parallel", type=int, default=1)
    parser.add_argument("--expert-parallel", type=int, default=1)
    parser.add_argument(
        "--lora-rank", type=int, default=0,
        help="train low-rank adapters over the frozen base (0 = full fine-tune)",
    )
    parser.add_argument(
        "--data", default="",
        help="flat int32 token .npy (workloads/data.py); synthetic if unset",
    )
    parser.add_argument(
        "--checkpoint-dir",
        default=os.environ.get("CHECKPOINT_DIR", ""),
        help="directory on a mounted volume for periodic checkpoints",
    )
    args = parser.parse_args()

    # Multi-host: the orchestrator injected the coordinator env; single
    # host: skip (jax.distributed would wait for peers).
    if int(os.environ.get("JAX_NUM_PROCESSES", "1")) > 1:
        jax.distributed.initialize()
    print(
        f"process {jax.process_index()}/{jax.process_count()} sees"
        f" {jax.local_device_count()} local / {jax.device_count()} global devices"
    )

    config = PRESETS[args.preset]
    if args.seq_len > config.max_seq_len:
        raise SystemExit(f"--seq-len > {config.max_seq_len} for {args.preset}")
    if args.expert_parallel > 1 and config.n_experts % args.expert_parallel:
        raise SystemExit("--expert-parallel must divide the preset's n_experts")
    mesh = make_mesh(
        jax.devices(), model=args.model_parallel, seq=args.seq_parallel,
        expert=args.expert_parallel,
    )
    # One state + one step either way; LoRA swaps in the tiny adapter
    # state and a step closed over the frozen base — data, checkpoints,
    # and the loop below are shared.
    if args.lora_rank > 0:
        from dstack_tpu.workloads.lora import (
            init_lora_state,
            make_lora_train_step,
            merge_lora,
        )
        from dstack_tpu.workloads.sharding import shard_tree
        from dstack_tpu.workloads.train import TrainState
        from dstack_tpu.workloads.transformer import init_params

        base = shard_tree(mesh, init_params(config, jax.random.PRNGKey(0)))
        state = init_lora_state(
            config, base, jax.random.PRNGKey(1), rank=args.lora_rank, mesh=mesh
        )
        _lora_step = make_lora_train_step(config, mesh, rank=args.lora_rank)

        def step(s, b):
            return _lora_step(s, base, b)

        def export(final_state):
            # Serve the merged model; checkpoints stored the adapters only.
            merged = merge_lora(base, final_state.lora, rank=args.lora_rank)
            ckpt.export_params(
                args.checkpoint_dir,
                TrainState(final_state.step, merged, None),
            )
    else:
        state = init_train_state(config, jax.random.PRNGKey(0), mesh=mesh)
        step = make_train_step(config, mesh)

        def export(final_state):
            ckpt.export_params(args.checkpoint_dir, final_state)

    if args.checkpoint_dir:
        # Resume from the mounted volume: a retried gang continues at the
        # last saved step instead of step 0 (dstack_tpu.workloads.checkpoint).
        restored = ckpt.restore_latest(args.checkpoint_dir, state)
        if restored is not None:
            state = restored
            if jax.process_index() == 0:
                print(f"resumed from step {int(state.step)}")

    # The global batch shards over the data+fsdp axes; round up so every
    # device gets at least one row.
    dp = mesh.shape["data"] * mesh.shape["fsdp"]
    batch_size = ((args.batch_size + dp - 1) // dp) * dp
    if batch_size != args.batch_size and jax.process_index() == 0:
        print(f"batch size {args.batch_size} -> {batch_size} (divisible by {dp})")
    loader = None
    if args.data:
        from dstack_tpu.workloads.data import BatchLoader, TokenDataset

        # The loader yields the GLOBAL batch; every host derives the same
        # order and materializes only its devices' shards (workloads/data.py).
        loader = BatchLoader(
            TokenDataset(args.data, args.seq_len),
            batch_size,
            mesh=mesh,
            start_step=int(state.step),
            vocab_size=config.vocab_size,
        )
    else:
        batch = synthetic_batch(config, batch_size, args.seq_len, mesh=mesh)

    start = int(state.step)  # nonzero after a resume
    for i in range(start, args.steps):
        if loader is not None:
            batch = next(loader)
        state, metrics = step(state, batch)
        if i % 10 == 0 or i == args.steps - 1:
            loss = float(metrics["loss"])
            if jax.process_index() == 0:
                print(f"step {i}: loss {loss:.4f}")
        ckpt_due = (i + 1) % 100 == 0 or i == args.steps - 1
        if args.checkpoint_dir and ckpt_due:
            # Every process participates (Orbax coordinates global arrays);
            # block on the final step so the job ends durable.
            ckpt.save(args.checkpoint_dir, state, wait=i == args.steps - 1)
    if args.checkpoint_dir:
        # Params-only export for serving (deployment/native/server.py reads
        # this without materializing optimizer moments).
        export(state)
        ckpt.close_all()  # drain async writers before the job exits
    if loader is not None:
        loader.close()
    print("training complete")


if __name__ == "__main__":
    main()
