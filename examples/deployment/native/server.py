"""Framework-native model server: the continuous-batching engine behind an
OpenAI-compatible HTTP API.

The JetStream/vLLM examples bring external engines; this one serves the
same llama-family checkpoints with dstack-tpu's own KV-cache decode loop
(workloads/generate.py) — the whole stack, orchestrator to tokens, is this
repo. Endpoints: GET /v1/models, POST /v1/chat/completions
(stream and non-stream), served by the continuous-batching engine
(workloads/serving.py).

The tokenizer here is a toy byte-level one so the example runs without
downloading a vocab (zero-egress test environments); swap in your
tokenizer for real checkpoints.
"""

import argparse
import codecs
import itertools
import json
import threading
import time
from collections import defaultdict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import jax

from dstack_tpu.dataplane.qos import (
    DEFAULT_TENANT,
    QoSGate,
    TenantShedError,
)
from dstack_tpu.server.tracing import HistogramData
from dstack_tpu.utils.stagemarkers import auto_stage
from dstack_tpu.utils.tracecontext import ensure_request_trace
from dstack_tpu.workloads import compile_cache
from dstack_tpu.workloads.config import PRESETS
from dstack_tpu.workloads.lora_serving import (
    AdapterBusyError,
    AdapterPoolFullError,
)
from dstack_tpu.workloads.serving import (
    EngineOverloadedError,
    ServingEngine,
    prometheus_metrics,
)
from dstack_tpu.workloads.transformer import init_params


class Engine:
    # Prompt lengths are bucketed so each bucket compiles ONCE — a fresh
    # XLA compile per novel prompt length would dominate request latency.
    MIN_BUCKET = 32

    def __init__(self, preset: str, max_new_tokens: int, checkpoint_dir: str = "",
                 quantize: str = "none", max_pending: int = 16,
                 slots: int = 8, steps_per_sync: int = 4,
                 max_prefills_per_chunk: int = 4,
                 prefill_chunk_tokens: int = 128, kv_block_size: int = 16,
                 spec_enable: bool = False, spec_max_draft: int = 4,
                 spec_draft_preset: str = "int8", kv_budget_mb: int = 0,
                 role: str = "unified", mesh_model: int = 1,
                 kv_transfer_connect: str = "",
                 lora_max_adapters: int = 0, lora_rank: int = 8,
                 adapters=None, qos_rate: float = 0.0,
                 qos_burst: float = 20.0, qos_tenant_cap: int = 64,
                 qos_weights=None, kv_host_budget_mb: int = 0,
                 max_resident_slots: int = 0,
                 trace_ring: int = 256, trace_slow_ms=None):
        self.config = PRESETS[preset]
        if max_new_tokens >= self.config.max_seq_len:
            raise SystemExit(
                f"--max-new-tokens {max_new_tokens} must be <"
                f" max_seq_len {self.config.max_seq_len} for {preset}"
            )
        self.max_new_tokens = max_new_tokens
        self._handoff_ids = itertools.count(1)
        auto_stage("weights_start")
        t_weights = time.monotonic()
        weights_via = "init"
        if checkpoint_dir:
            from dstack_tpu.workloads import checkpoint as ckpt

            # Cold-start order: packed export first (mmap + parallel
            # device_put — the scale-from-zero fast path), then the
            # params-only Orbax export, then a full train-state restore.
            params = ckpt.load_packed(checkpoint_dir)
            if params is not None:
                weights_via = "packed-parallel"
            else:
                from dstack_tpu.workloads.transformer import init_params as _init

                template = _init(self.config, jax.random.PRNGKey(0))
                params = ckpt.restore_exported_params(checkpoint_dir, template)
                if params is not None:
                    weights_via = "orbax-export"
                else:
                    from dstack_tpu.workloads.train import init_train_state

                    state_tpl = init_train_state(
                        self.config, jax.random.PRNGKey(0)
                    )
                    restored = ckpt.restore_latest(checkpoint_dir, state_tpl)
                    if restored is not None:
                        params = restored.params
                        weights_via = "orbax-train"
                    else:
                        params = template
            self.params = params
        else:
            self.params = init_params(self.config, jax.random.PRNGKey(0))
        jax.block_until_ready(jax.tree_util.tree_leaves(self.params)[0])
        auto_stage("weights_end")
        self.weights_seconds = time.monotonic() - t_weights
        self.weights_via = weights_via
        print(
            f"weights: loaded in {self.weights_seconds:.2f}s"
            f" via {weights_via}", flush=True,
        )
        if quantize == "int8":
            # Weight-only int8: decode is weight-bandwidth-bound, so the
            # smaller HBM reads buy ~1.25x decode throughput (measured on
            # v5e) at ~half the weight memory (workloads/quant.py).
            from dstack_tpu.workloads.quant import quantize_params

            self.params = quantize_params(self.params)
        # Continuous batching: concurrent requests share one decode batch
        # (workloads/serving.py) instead of queueing behind each other.
        # Bounded admission: beyond max_pending queued requests the API
        # answers 429 + Retry-After rather than letting TTFT blow up
        # (measured: 10.8 s TTFT p50 at 2x oversubscription unbounded).
        # Scheduler knobs ride through from the CLI: `slots` (decode
        # batch width), `steps_per_sync` (device steps per host
        # readback), and `max_prefills_per_chunk` (admissions per chunk
        # boundary — the overlapped scheduler's fairness knob). See
        # docs/guides/serving-tuning.md for the measured trade-offs.
        # Paged-KV knobs: `prefill_chunk_tokens` bounds the prompt
        # tokens computed per chunk boundary (decode stall ceiling), and
        # `kv_block_size` is the pool's block granularity (must divide
        # the preset's max_seq_len). The engine validates both; surface
        # its ValueError as a clean CLI error, not a traceback.
        # Speculative decoding: the drafter is either an int8-quantized
        # copy of the target (default — same architecture, cheaper math,
        # high acceptance) or a smaller preset drafting for a bigger
        # target. The engine builds the int8 drafter itself when no
        # drafter params are passed.
        draft_params = draft_config = None
        if spec_enable and spec_draft_preset != "int8":
            draft_config = PRESETS[spec_draft_preset]
            draft_params = init_params(draft_config, jax.random.PRNGKey(1))
        # Tensor parallelism: shard the target (and drafter) weights plus
        # the paged KV pools over a `model` mesh axis. The column-parallel
        # specs keep contractions replicated, so a sharded server is
        # token-bit-exact with the single-device one (no logic forks).
        mesh = None
        if mesh_model > 1:
            from dstack_tpu.workloads.sharding import make_mesh

            devs = jax.devices()
            if len(devs) < mesh_model:
                raise SystemExit(
                    f"--mesh-model {mesh_model} needs that many devices,"
                    f" have {len(devs)}"
                )
            mesh = make_mesh(devs[:mesh_model], model=mesh_model)
        # Prefill/decode disaggregation: a prefill-tier server computes
        # chunked prefill on its own devices and ships finished KV blocks
        # to the decode tier over the kv_transfer seam; its chat API acks
        # with finish_reason "kv_handoff" (tokens stream from the decode
        # tier — see /v1/handoffs/<id> there).
        kv_transfer = None
        if role == "prefill":
            if not kv_transfer_connect:
                raise SystemExit(
                    "--role prefill requires --kv-transfer-connect host:port"
                )
            from dstack_tpu.workloads.kv_transfer import TransferClient

            host, _, port = kv_transfer_connect.rpartition(":")
            try:
                kv_transfer = TransferClient(host or "127.0.0.1", int(port))
            except ValueError:
                raise SystemExit(
                    f"--kv-transfer-connect {kv_transfer_connect!r} is not"
                    " host:port"
                )
        try:
            self.serving = ServingEngine(
                self.config, self.params, slots=slots, temperature=0.8,
                max_pending=max_pending, steps_per_sync=steps_per_sync,
                max_prefills_per_chunk=max_prefills_per_chunk,
                prefill_chunk_tokens=prefill_chunk_tokens,
                kv_block_size=kv_block_size,
                spec_enable=spec_enable, spec_max_draft=spec_max_draft,
                spec_draft_params=draft_params,
                spec_draft_config=draft_config,
                kv_budget_bytes=kv_budget_mb * (1 << 20) or None,
                mesh=mesh, role=role, kv_transfer=kv_transfer,
                lora_max_adapters=lora_max_adapters, lora_rank=lora_rank,
                trace_ring=trace_ring, trace_slow_ms=trace_slow_ms,
                # Hierarchical KV: LRU-evicted prefix blocks spill to a
                # host-RAM tier instead of dying, and admitted streams may
                # overcommit the HBM-resident slot count (preempted slots
                # swap their whole KV chain to host and resume later).
                kv_host_budget_bytes=kv_host_budget_mb * (1 << 20) or None,
                max_resident_slots=max_resident_slots or None,
                qos_weights=qos_weights or None,
            )
        except ValueError as e:
            raise SystemExit(f"invalid serving configuration: {e}")
        # --adapter name=path entries: "random" makes a demo adapter in
        # process (tests, zero-egress environments); anything else is a
        # save_adapter npz carrying its own rank/alpha.
        self.lora_rank = lora_rank
        for entry in adapters or ():
            name, _, path = entry.partition("=")
            if not name or not path:
                raise SystemExit(f"--adapter {entry!r} is not name=path")
            try:
                self.load_adapter(name, path)
            except (ValueError, RuntimeError, OSError) as e:
                raise SystemExit(f"--adapter {entry!r}: {e}")
        # Per-tenant QoS in front of submit: token buckets shed floods
        # (429 + Retry-After), the DRR queue orders admission under
        # contention for the decode slots. Off unless --qos-rate > 0.
        self.qos = None
        if qos_rate > 0:
            self.qos = QoSGate(
                rate=qos_rate, burst=qos_burst, tenant_cap=qos_tenant_cap,
                weights=qos_weights or None,
                concurrency=max(slots, max_pending),
            )
        # Per-tenant observability (bounded cardinality via the gate's
        # TenantLabels when QoS is on, else a private mapping).
        from dstack_tpu.dataplane.qos import TenantLabels

        self.tenant_labels = (
            self.qos.labels if self.qos is not None
            else TenantLabels(cap=qos_tenant_cap)
        )
        self._tenant_lock = threading.Lock()
        self.tenant_requests = defaultdict(int)
        self.tenant_shed = defaultdict(int)
        self.tenant_ttft = defaultdict(HistogramData)

    def load_adapter(self, name: str, path: str, alpha: float = 16.0) -> int:
        """Load a LoRA adapter into the pool: `path` is a save_adapter
        npz, or the literal "random" for an in-process demo adapter.
        Returns the device pool slot the adapter landed in."""
        from dstack_tpu.workloads.lora_serving import (
            demo_adapter, load_adapter_file,
        )

        if path == "random":
            seed = abs(hash(name)) % (2 ** 31)
            tree = demo_adapter(
                self.config, self.params, jax.random.PRNGKey(seed),
                rank=self.lora_rank, targets=("wq", "wv"),
            )
            return self.serving.load_adapter(name, tree, alpha=alpha)
        tree, rank, file_alpha = load_adapter_file(path)
        if rank != self.lora_rank:
            raise ValueError(
                f"adapter {name!r} has rank {rank}, engine pool is"
                f" rank {self.lora_rank}"
            )
        return self.serving.load_adapter(name, tree, alpha=file_alpha)

    def record_tenant(self, tenant: str, *, shed: bool = False,
                      ttft: float = None) -> None:
        label = self.tenant_labels.label(tenant or DEFAULT_TENANT)
        with self._tenant_lock:
            if shed:
                self.tenant_shed[label] += 1
            else:
                self.tenant_requests[label] += 1
            if ttft is not None:
                self.tenant_ttft[label].observe(ttft)

    def tenant_metrics_lines(self) -> list:
        """Per-tenant Prometheus series appended to the engine's
        exposition (series declared in server/metrics_registry.py)."""
        lines = []
        with self._tenant_lock:
            req = sorted(self.tenant_requests.items())
            shed = sorted(self.tenant_shed.items())
            ttft = sorted(
                (t, h.to_dict()) for t, h in self.tenant_ttft.items()
            )
        lines.append("# TYPE dstack_tpu_serving_tenant_requests_total counter")
        for t, n in req:
            lines.append(
                f'dstack_tpu_serving_tenant_requests_total{{tenant="{t}"}} {n}'
            )
        lines.append("# TYPE dstack_tpu_serving_tenant_shed_total counter")
        for t, n in shed:
            lines.append(
                f'dstack_tpu_serving_tenant_shed_total{{tenant="{t}"}} {n}'
            )
        base = "dstack_tpu_serving_tenant_ttft_seconds"
        lines.append(f"# TYPE {base} histogram")
        for t, h in ttft:
            for le, cum in h["buckets"]:
                lines.append(
                    f'{base}_bucket{{le="{le}",tenant="{t}"}} {cum}'
                )
            lines.append(
                f'{base}_bucket{{le="+Inf",tenant="{t}"}} {h["count"]}'
            )
            lines.append(f'{base}_sum{{tenant="{t}"}} {h["sum"]}')
            lines.append(f'{base}_count{{tenant="{t}"}} {h["count"]}')
        return lines

    def encode(self, text: str):
        ids = [min(b, self.config.vocab_size - 1) for b in text.encode()] or [0]
        limit = self.config.max_seq_len - self.max_new_tokens
        ids = ids[-limit:] if limit > 0 else ids[:1]
        # Bucket to a power of two: pad short prompts left with newline
        # bytes, truncate the OLDEST bytes down to the bucket otherwise.
        bucket = self.MIN_BUCKET
        while bucket * 2 <= len(ids):
            bucket *= 2
        bucket = min(bucket, limit if limit > 0 else bucket)
        if len(ids) < bucket:
            ids = [10] * (bucket - len(ids)) + ids
        else:
            ids = ids[-bucket:]
        # Host-side (1, bucket) nested list, NOT a device array: the
        # engine takes a token list, and a device round-trip here would
        # build four tiny jit programs per novel bucket — compiles the
        # warmup pass can't see, breaking the zero-post-ready contract.
        return [ids]

    def decode(self, ids) -> str:
        return bytes(int(t) % 256 for t in ids).decode("utf-8", errors="replace")

    def chat_stream(self, messages, max_tokens=None, temperature=None,
                    top_p=None, stop=None, usage_out=None,
                    adapter=None, tenant=None,
                    traceparent=None, x_request_id=None):
        """Yield decoded text fragments as tokens land (continuous batch).

        `max_tokens` and `temperature` are the per-request OpenAI fields:
        the budget is clamped to the server's --max-new-tokens cap (which
        also bounds the KV rows a request can occupy); temperature rides
        per-SLOT through the decode batch (0 = greedy). UTF-8 is decoded
        incrementally so multi-byte characters split across tokens
        reassemble instead of degrading to U+FFFD."""
        budget = self.max_new_tokens
        if max_tokens is not None:
            try:
                budget = max(1, min(int(max_tokens), self.max_new_tokens))
            except (TypeError, ValueError):
                pass  # malformed client value: serve with the server cap
        temp = None
        if temperature is not None:
            try:
                v = float(temperature)
                # max(0.0, nan) is 0.0 — NaN would silently mean GREEDY
                # instead of "malformed: engine default" (the engine
                # itself rejects NaN with 400; match the top_p branch).
                if v == v and v != float("inf"):
                    temp = max(0.0, v)
            except (TypeError, ValueError):
                pass  # malformed: engine default
        nucleus = 1.0
        if top_p is not None:
            try:
                v = float(top_p)
                # NaN slips through min/max (max(nan, x) is nan): treat it
                # like any other malformed value — no filtering.
                if v == v:
                    nucleus = min(max(v, 1e-6), 1.0)
            except (TypeError, ValueError):
                pass  # malformed: no filtering
        prompt = "\n".join(
            f"{m.get('role', 'user')}: {m.get('content', '')}" for m in messages
        )
        if isinstance(stop, str):
            stops = [stop]
        elif isinstance(stop, (list, tuple)):
            stops = [x for x in stop if isinstance(x, str) and x]
        else:
            stops = []  # malformed: no stop filtering (lenient like temp)
        tokens = self.encode(prompt + "\nassistant:")
        if usage_out is not None:
            # OpenAI usage accounting: real engine token counts, not a
            # re-tokenization guess (byte vocab: one token per byte).
            usage_out["prompt_tokens"] = len(tokens[0])
            usage_out["completion_tokens"] = 0
        rid = None
        if self.serving.role == "prefill":
            # Correlation id carried on the KV handoff: the front-end
            # fetches the stream from the decode tier at
            # GET /v1/handoffs/<id>.
            rid = next(self._handoff_ids)
            if usage_out is not None:
                usage_out["handoff_id"] = rid
        # Arrival timestamp BEFORE QoS admission, so the flight recorder
        # can attribute gate time to its own `qos_admission` phase.
        t_arrival = time.monotonic()
        granted = False
        if self.qos is not None:
            # Sheds (TenantShedError -> 429) or blocks for the tenant's
            # DRR turn at a grant permit; the permit frees in `finally`.
            try:
                self.qos.admit(tenant or DEFAULT_TENANT)
            except TenantShedError:
                # Shed before the engine ever saw it: a one-shot terminal
                # trace so the tail capture still records the rejection.
                self.serving.recorder.record_dropped(
                    x_request_id, x_request_id=x_request_id,
                    traceparent=traceparent, t0=t_arrival,
                )
                raise
            granted = True
        t_submit = time.monotonic()
        ttft_seen = False
        try:
            out = self.serving.submit(
                list(tokens[0]), max_new_tokens=budget,
                temperature=temp, top_p=nucleus, request_id=rid,
                adapter=adapter, traceparent=traceparent,
                x_request_id=x_request_id,
                t_arrival=t_arrival if self.qos is not None else None,
                # On a host-tier engine with --qos-weight, a heavier
                # tenant's request may preempt a lighter tenant's live
                # slot (KV swap-out) instead of queueing behind it.
                tenant=tenant or DEFAULT_TENANT,
            )
        except BaseException:
            if granted:
                self.qos.release()
            raise
        self.record_tenant(tenant)
        dec = codecs.getincrementaldecoder("utf-8")("replace")
        # Streaming stop matching: text already sent cannot be unsent, so
        # hold back any suffix that is a PREFIX of a stop sequence until
        # it either completes the stop (truncate + free the slot) or
        # diverges (flush). The buffer never exceeds max stop length + one
        # piece, so scans are O(stop length) per token, and OpenAI
        # semantics hold: the stop string itself is never emitted.
        max_hold = max((len(x) for x in stops), default=1) - 1

        def holdback(b):
            for k in range(min(max_hold, len(b)), 0, -1):
                tail = b[-k:]
                if any(x.startswith(tail) for x in stops):
                    return k
            return 0

        buf = ""
        try:
            while True:
                tok = out.get()
                if isinstance(tok, BaseException):
                    raise RuntimeError(f"generation failed: {tok}")
                if tok is None:
                    buf += dec.decode(b"", True)
                    if buf:
                        yield buf  # incomplete stop prefix at end: emit
                    if (self.serving.role == "prefill" and budget > 1
                            and usage_out is not None
                            and not usage_out.get("completion_tokens")):
                        # Handed off: the prefill tier never streams
                        # tokens (the sampled first token travels inside
                        # the KV handoff); this response is the ack.
                        usage_out["finish_reason"] = "kv_handoff"
                    return
                if not ttft_seen:
                    ttft_seen = True
                    self.record_tenant(
                        tenant, ttft=time.monotonic() - t_submit
                    )
                if usage_out is not None:
                    usage_out["completion_tokens"] += 1
                piece = dec.decode(bytes([int(tok) % 256]))
                if not piece:
                    continue
                if not stops:
                    yield piece
                    continue
                buf += piece
                hit = -1
                for x in stops:
                    i = buf.find(x)
                    if i >= 0 and (hit < 0 or i < hit):
                        hit = i
                if hit >= 0:
                    if buf[:hit]:
                        yield buf[:hit]
                    if usage_out is not None:
                        # OpenAI semantics: clients branch on this —
                        # "length" makes them retry/continue a completion
                        # that actually ended cleanly on a stop sequence.
                        usage_out["finish_reason"] = "stop"
                    self.serving.cancel(out)  # free the slot early
                    return
                keep = holdback(buf)
                if len(buf) > keep:
                    yield buf[:len(buf) - keep]
                    buf = buf[len(buf) - keep:] if keep else ""
        finally:
            # Consumer gone mid-stream (client disconnect closes this
            # generator) or stop hit: the engine must not keep decoding
            # into a queue nobody reads. Idempotent after clean end.
            self.serving.cancel(out)
            if granted:
                self.qos.release()

    def chat(self, messages, max_tokens=None, temperature=None, top_p=None,
             stop=None, usage_out=None, adapter=None, tenant=None,
             traceparent=None, x_request_id=None) -> str:
        return "".join(self.chat_stream(messages, max_tokens, temperature,
                                        top_p, stop, usage_out=usage_out,
                                        adapter=adapter, tenant=tenant,
                                        traceparent=traceparent,
                                        x_request_id=x_request_id))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--preset", default="smol-1b", choices=sorted(PRESETS))
    parser.add_argument("--port", type=int, default=9000)
    parser.add_argument("--model-name", default="dstack-tpu-native")
    parser.add_argument("--max-new-tokens", type=int, default=64)
    parser.add_argument("--checkpoint-dir", default="",
                        help="volume path with a checkpoint to serve: a"
                             " save_packed export (mmap + parallel load,"
                             " the cold-start fast path) or an Orbax"
                             " checkpoint")
    parser.add_argument("--compile-cache-dir", default="",
                        help="persistent XLA compile-cache base dir (a"
                             " durable volume path): repeat boots retrieve"
                             " compiled programs from disk instead of"
                             " recompiling. Keyed by jax+jaxlib version"
                             " and backend under the base, so one volume"
                             " serves heterogeneous workers. Defaults to"
                             " $DSTACK_TPU_COMPILE_CACHE when unset")
    parser.add_argument("--no-warmup", action="store_true",
                        help="skip the warmup pass that pre-compiles every"
                             " jitted engine program before /readyz flips"
                             " ready (warmup is on by default; without it"
                             " the first unlucky requests pay compilation)")
    parser.add_argument("--quantize", default="none", choices=["none", "int8"],
                        help="weight-only int8 for ~1.25x decode throughput")
    parser.add_argument("--max-pending", type=int, default=16,
                        help="queued-request bound; overflow answers 429")
    parser.add_argument("--slots", type=int, default=8,
                        help="decode batch width (concurrent streams)")
    parser.add_argument("--steps-per-sync", type=int, default=4,
                        help="device decode steps per host readback")
    parser.add_argument("--max-prefills-per-chunk", type=int, default=4,
                        help="admissions per decode chunk boundary (the"
                             " overlapped scheduler's fairness knob)")
    parser.add_argument("--prefill-chunk-tokens", type=int, default=128,
                        help="prompt tokens computed per chunk boundary —"
                             " bounds the decode stall a long prompt causes")
    parser.add_argument("--kv-block-size", type=int, default=16,
                        help="paged-KV block granularity in tokens; must"
                             " divide the preset's max_seq_len")
    parser.add_argument("--spec-enable", action="store_true",
                        help="draft-model speculative decoding: a cheap"
                             " drafter proposes tokens, the target verifies"
                             " them in one forward (distribution-exact)")
    parser.add_argument("--spec-max-draft", type=int, default=4,
                        help="ceiling for the adaptive per-slot draft length")
    parser.add_argument("--spec-draft-preset", default="int8",
                        help="drafter model: 'int8' (quantized copy of the"
                             " target) or a smaller preset name")
    parser.add_argument("--role", default="unified",
                        choices=["unified", "prefill", "decode"],
                        help="serving tier: unified (default) runs prefill"
                             " and decode in-process; prefill ships finished"
                             " KV blocks to the decode tier; decode admits"
                             " handed-off requests on --kv-transfer-port")
    parser.add_argument("--mesh-model", type=int, default=1,
                        help="tensor-parallel shards over a `model` mesh"
                             " axis (weights + paged KV pools; bit-exact"
                             " with 1)")
    parser.add_argument("--kv-transfer-port", type=int, default=0,
                        help="decode role: port the KV transfer server"
                             " listens on for prefill-tier handoffs")
    parser.add_argument("--kv-transfer-connect", default="",
                        help="prefill role: host:port of the decode tier's"
                             " KV transfer server")
    parser.add_argument("--kv-budget-mb", type=int, default=0,
                        help="KV pool memory budget in MiB (0 = unlimited);"
                             " with --spec-enable the target AND drafter"
                             " pools must both fit")
    parser.add_argument("--kv-host-budget-mb", type=int, default=0,
                        help="host-RAM KV tier budget in MiB (0 = no host"
                             " tier): LRU-evicted prefix-cache blocks spill"
                             " here instead of dying, and preempted slots"
                             " park their live KV chain here until resume")
    parser.add_argument("--max-resident-slots", type=int, default=0,
                        help="HBM-resident decode slot cap (0 = --slots):"
                             " setting it below --slots overcommits"
                             " admission — the engine round-robins more"
                             " admitted streams than fit in HBM by swapping"
                             " slot KV through the host tier (requires"
                             " --kv-host-budget-mb)")
    parser.add_argument("--qos-weight", action="append", default=[],
                        metavar="TENANT=WEIGHT",
                        help="per-tenant DRR weight (repeatable; default"
                             " 1.0): orders admission under contention and,"
                             " with --kv-host-budget-mb, lets a heavier"
                             " tenant preempt a lighter tenant's live slot"
                             " (KV swap-out) mid-generation")
    parser.add_argument("--adapter", action="append", default=[],
                        metavar="NAME=PATH",
                        help="preload a LoRA adapter (repeatable);"
                             " PATH is an .npz from save_adapter, or"
                             " 'random' for a demo adapter. Request it"
                             " via model='<model-name>:<NAME>'")
    parser.add_argument("--lora-max-adapters", type=int, default=0,
                        help="device adapter-pool slots; 0 disables LoRA"
                             " multiplexing (defaults to len(--adapter)"
                             " when adapters are given)")
    parser.add_argument("--lora-rank", type=int, default=8,
                        help="rank of the device adapter pool; every"
                             " loaded adapter must match it")
    parser.add_argument("--qos-rate", type=float, default=0.0,
                        help="per-tenant token-bucket refill rate"
                             " (requests/s); 0 disables QoS admission")
    parser.add_argument("--qos-burst", type=float, default=20.0,
                        help="per-tenant token-bucket capacity")
    parser.add_argument("--qos-tenant-cap", type=int, default=64,
                        help="distinct tenant labels before metrics"
                             " collapse into the overflow label")
    parser.add_argument("--trace-ring", type=int, default=256,
                        help="flight-recorder ring size (retained request"
                             " traces); 0 disables per-request tracing")
    parser.add_argument("--trace-slow-ms", type=float, default=None,
                        help="tail-based capture threshold: full traces"
                             " persist only for requests at/above this"
                             " many ms or ending in error/shed (unset"
                             " disables tail capture)")
    args = parser.parse_args()
    if args.adapter and args.lora_max_adapters <= 0:
        args.lora_max_adapters = len(args.adapter)
    if args.spec_max_draft <= 0:
        raise SystemExit(
            f"--spec-max-draft must be positive, got {args.spec_max_draft}"
        )
    if args.spec_draft_preset != "int8" and args.spec_draft_preset not in PRESETS:
        raise SystemExit(
            f"--spec-draft-preset {args.spec_draft_preset!r} is not a known"
            f" preset (choose 'int8' or one of: {', '.join(sorted(PRESETS))})"
        )
    if args.prefill_chunk_tokens <= 0:
        raise SystemExit(
            f"--prefill-chunk-tokens must be positive,"
            f" got {args.prefill_chunk_tokens}"
        )
    if args.kv_block_size <= 0:
        raise SystemExit(
            f"--kv-block-size must be positive, got {args.kv_block_size}"
        )
    max_len = PRESETS[args.preset].max_seq_len
    if max_len % args.kv_block_size != 0:
        raise SystemExit(
            f"--kv-block-size {args.kv_block_size} must divide"
            f" {args.preset}'s max_seq_len {max_len}"
        )

    if args.role == "decode" and not args.kv_transfer_port:
        raise SystemExit("--role decode requires --kv-transfer-port")
    if args.max_resident_slots and not args.kv_host_budget_mb:
        raise SystemExit(
            "--max-resident-slots overcommit needs --kv-host-budget-mb"
            " (swapped-out slots park their KV in the host tier)"
        )
    qos_weights = {}
    for entry in args.qos_weight:
        tenant, _, weight = entry.partition("=")
        try:
            qos_weights[tenant] = float(weight)
        except ValueError:
            weight = ""
        if not tenant or not weight or qos_weights[tenant] <= 0:
            raise SystemExit(
                f"--qos-weight {entry!r} is not TENANT=WEIGHT"
                " with a positive weight"
            )
    # The cache must be live before the Engine constructor touches the
    # accelerator — weight init and the warmup pass below both compile.
    if args.compile_cache_dir:
        leaf = compile_cache.enable(args.compile_cache_dir)
    else:
        leaf = compile_cache.enable_from_env()
    if leaf:
        print(f"compile cache: {leaf}", flush=True)
    engine = Engine(args.preset, args.max_new_tokens, args.checkpoint_dir,
                    quantize=args.quantize, max_pending=args.max_pending,
                    slots=args.slots, steps_per_sync=args.steps_per_sync,
                    max_prefills_per_chunk=args.max_prefills_per_chunk,
                    prefill_chunk_tokens=args.prefill_chunk_tokens,
                    kv_block_size=args.kv_block_size,
                    spec_enable=args.spec_enable,
                    spec_max_draft=args.spec_max_draft,
                    spec_draft_preset=args.spec_draft_preset,
                    kv_budget_mb=args.kv_budget_mb,
                    role=args.role, mesh_model=args.mesh_model,
                    kv_transfer_connect=args.kv_transfer_connect,
                    lora_max_adapters=args.lora_max_adapters,
                    lora_rank=args.lora_rank, adapters=args.adapter,
                    qos_rate=args.qos_rate, qos_burst=args.qos_burst,
                    qos_tenant_cap=args.qos_tenant_cap,
                    qos_weights=qos_weights,
                    kv_host_budget_mb=args.kv_host_budget_mb,
                    max_resident_slots=args.max_resident_slots,
                    trace_ring=args.trace_ring,
                    trace_slow_ms=args.trace_slow_ms)

    # Warmup-gated readiness: /readyz answers 503 until the engine's
    # warmup pass has built every jitted program, so an orchestrator that
    # waits for ready before routing guarantees no request ever pays a
    # compile (docs/guides/serving-tuning.md, "cold start"). /healthz is
    # liveness only and is green the moment the socket is up.
    ready = threading.Event()

    # Decode tier: admit prefill-tier handoffs and expose each admitted
    # stream at GET /v1/handoffs/<request_id> (SSE) for the front-end to
    # collect. Streams are parked until claimed; a claim is exclusive.
    handoff_streams = {}
    handoff_lock = threading.Lock()

    # Affinity-sketch gossip is pull-based: every data-plane worker
    # fetches /v1/affinity once per epoch poll, so the cost of serving
    # it scales with fleet-wide worker count x poll rate. A short TTL
    # cache bounds that cost at one sketch build per TTL no matter how
    # many workers poll, and keeps gossip from contending with
    # generation steps on a busy engine. Staleness it adds (<= the TTL)
    # is far inside the one-poll-interval staleness bound routers
    # already tolerate.
    sketch_cache = {"at": 0.0, "body": None}
    sketch_cache_ttl = 0.25
    sketch_lock = threading.Lock()
    transfer_server = None
    if args.role == "decode":
        from dstack_tpu.workloads.kv_transfer import TransferServer

        def _on_handoff(h):
            out = engine.serving.submit_prefilled(h)
            with handoff_lock:
                handoff_streams[h.request_id] = out

        transfer_server = TransferServer(
            "0.0.0.0", args.kv_transfer_port, _on_handoff,
            epoch=engine.serving.handoff_epoch,
        )
        print(f"kv transfer server on :{transfer_server.port}", flush=True)

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _trace_identity(self):
            """(traceparent, request_id) for this request: the inbound
            headers when valid, minted otherwise. Computed per call — a
            handler instance has no per-request state to cache in."""
            hdrs = {k.lower(): v for k, v in self.headers.items()}
            return ensure_request_trace({}, hdrs)

        def _send(self, code: int, obj, headers=()) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            tp, req_id = self._trace_identity()
            self.send_header("X-Request-ID", req_id)
            self.send_header("Traceparent", tp)
            for k, v in headers:
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _send_overloaded(self, e: EngineOverloadedError) -> None:
            self._send(
                429,
                {"error": {"message": str(e), "type": "overloaded",
                           "retry_after": e.retry_after}},
                headers=[("Retry-After", str(int(e.retry_after + 0.5) or 1))],
            )

        def _send_shed(self, e: TenantShedError) -> None:
            engine.record_tenant(e.tenant, shed=True)
            self._send(
                429,
                {"error": {"message": str(e), "type": "rate_limited",
                           "tenant": e.tenant,
                           "retry_after": e.retry_after}},
                headers=[("Retry-After", str(max(1, int(e.retry_after + 0.5))))],
            )

        def _request_identity(self, req):
            """(adapter, tenant) for this request: the OpenAI `model`
            field selects the adapter (`base:adapter`); tenancy is the
            API key when one was sent, else the adapter name, else the
            shared default bucket — the same identity the engine's
            prefix cache namespaces KV by."""
            model = req.get("model") or ""
            adapter = None
            if ":" in model:
                adapter = model.split(":", 1)[1] or None
            auth = self.headers.get("Authorization", "")
            tenant = None
            if auth.lower().startswith("bearer "):
                tenant = auth[7:].strip() or None
            return adapter, tenant or adapter or DEFAULT_TENANT

        def _stream(self, req) -> None:
            """OpenAI-style SSE: one delta chunk per generated token."""
            # Pull the first piece BEFORE committing the 200/SSE headers, so
            # submit-time errors surface as a clean JSON 500 instead of a
            # second status line spliced into the event stream.
            adapter, tenant = self._request_identity(req)
            tp, req_id = self._trace_identity()
            try:
                pieces = engine.chat_stream(
                    req.get("messages", []), req.get("max_tokens"),
                    req.get("temperature"), req.get("top_p"), req.get("stop"),
                    adapter=adapter, tenant=tenant,
                    traceparent=tp, x_request_id=req_id,
                )
                first = next(pieces)
            except StopIteration:
                first = ""
            except TenantShedError as e:
                return self._send_shed(e)
            except EngineOverloadedError as e:
                engine.record_tenant(tenant, shed=True)
                return self._send_overloaded(e)
            except KeyError as e:  # unknown adapter
                return self._send(404, {"error": f"unknown adapter: {e}"})
            except ValueError as e:  # bad request field (e.g. temperature)
                return self._send(400, {"error": str(e)})
            except Exception as e:
                return self._send(500, {"error": str(e)})
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("X-Request-ID", req_id)
            self.send_header("Traceparent", tp)
            self.end_headers()
            try:
                self._stream_body(first, pieces, req_id)
            except Exception:
                # Headers are committed: a 500 here would splice a second
                # status line into the event stream. Truncating WITHOUT the
                # [DONE] terminator is the SSE convention for "broken".
                return

        def _stream_body(self, first, pieces, req_id=None) -> None:
            for i, piece in enumerate(itertools.chain([first], pieces)):
                chunk = {
                    "id": "chatcmpl-native",
                    "object": "chat.completion.chunk",
                    "created": int(time.time()),
                    "model": args.model_name,
                    "choices": [{
                        "index": 0,
                        "delta": {"content": piece} if i else
                                 {"role": "assistant", "content": piece},
                        "finish_reason": None,
                    }],
                }
                self.wfile.write(b"data: " + json.dumps(chunk).encode() + b"\n\n")
                self.wfile.flush()
            # Final usage-style block: the flight recorder's phase summary
            # for this stream, so the client sees where its latency went
            # without a second round trip to the trace endpoint.
            trace = (engine.serving.request_trace(req_id)
                     if req_id is not None else None)
            if trace is not None:
                summary = {
                    "id": "chatcmpl-native",
                    "object": "chat.completion.chunk",
                    "created": int(time.time()),
                    "model": args.model_name,
                    # An empty-delta choice rather than `"choices": []`:
                    # clients that index choices[0] unconditionally (the
                    # common SSE-consumer shape) must survive this chunk.
                    "choices": [{"index": 0, "delta": {},
                                 "finish_reason": None}],
                    "phase_summary": {
                        "request_id": trace["request_id"],
                        "trace_id": trace["trace_id"],
                        "total_seconds": trace["total_seconds"],
                        "phases": trace["phases"],
                        "counters": trace["counters"],
                    },
                }
                self.wfile.write(
                    b"data: " + json.dumps(summary).encode() + b"\n\n"
                )
                self.wfile.flush()
            self.wfile.write(b"data: [DONE]\n\n")

        def do_GET(self):
            if self.path.rstrip("/") == "/healthz":
                return self._send(200, {"ok": True})
            if self.path.rstrip("/") == "/readyz":
                if ready.is_set():
                    stats = engine.serving.stats()
                    return self._send(200, {
                        "ready": True,
                        "warmup_seconds": stats.get("warmup_seconds"),
                        "weights_seconds": round(engine.weights_seconds, 3),
                        "weights_via": engine.weights_via,
                    })
                return self._send(
                    503,
                    {"ready": False, "phase": "warmup"},
                    headers=[("Retry-After", "2")],
                )
            if self.path.rstrip("/") == "/v1/models":
                # Loaded adapters list as models in their own right
                # (`base:adapter`), mirroring the control-plane proxy's
                # routing-cache expansion.
                data = [{"id": args.model_name, "object": "model",
                         "created": 0, "owned_by": "dstack-tpu"}]
                if engine.serving.lora_enabled:
                    for name in sorted(engine.serving.adapters()):
                        data.append({
                            "id": f"{args.model_name}:{name}",
                            "object": "model", "created": 0,
                            "owned_by": "dstack-tpu",
                        })
                return self._send(200, {"object": "list", "data": data})
            path, _, query = self.path.partition("?")
            if path.rstrip("/") == "/v1/affinity":
                # Cache-affinity sketch for fleet routing: resident
                # prefix chain-head digests + loaded adapters, plus the
                # tokenizer parameters a router needs to recompute the
                # SAME chain keys over the SAME block boundaries
                # (tokenizer-consistency is what makes the scores mean
                # "expected matched blocks"). Cheap: no device work,
                # one pass over the host-side cache index, served from a
                # short TTL cache so N polling workers cost one build.
                with sketch_lock:
                    now = time.monotonic()
                    if (sketch_cache["body"] is None
                            or now - sketch_cache["at"] > sketch_cache_ttl):
                        sketch_cache["body"] = {
                            **engine.serving.affinity_sketch(),
                            "model": args.model_name,
                            "tokenizer": {
                                "kind": "byte",
                                "vocab_size": engine.config.vocab_size,
                                "prompt_limit": (
                                    engine.config.max_seq_len
                                    - engine.max_new_tokens
                                ),
                                "min_bucket": Engine.MIN_BUCKET,
                            },
                        }
                        sketch_cache["at"] = now
                    body = sketch_cache["body"]
                return self._send(200, body)
            if path.rstrip("/") == "/metrics":
                # Queue depth, shed counters, and paged-KV pool gauges
                # for scrapers and the control plane's autoscaler
                # signals. JSON by default (existing consumers);
                # Prometheus text when the scraper asks for it via
                # Accept or ?format=prometheus.
                stats = engine.serving.stats()
                accept = self.headers.get("Accept", "")
                if "format=prometheus" in query or "text/plain" in accept:
                    text = prometheus_metrics(stats)
                    tenant_lines = engine.tenant_metrics_lines()
                    if tenant_lines:
                        text = text.rstrip("\n") + "\n" + \
                            "\n".join(tenant_lines) + "\n"
                    body = text.encode()
                    self.send_response(200)
                    self.send_header(
                        "Content-Type", "text/plain; version=0.0.4"
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if engine.qos is not None:
                    stats = {**stats, "qos": engine.qos.stats()}
                return self._send(200, stats)
            if path.rstrip("/").startswith("/v1/handoffs/"):
                return self._stream_handoff(path.rstrip("/"))
            clean = path.rstrip("/")
            if clean.startswith("/v1/requests/") and clean.endswith("/trace"):
                # Phase timeline by engine request id or client
                # X-Request-ID (live ring first, then the tail store).
                rid = clean[len("/v1/requests/"):-len("/trace")]
                trace = engine.serving.request_trace(rid)
                if trace is None:
                    return self._send(
                        404, {"error": f"no trace for request {rid!r}"}
                    )
                return self._send(200, trace)
            self._send(404, {"error": "not found"})

        def _stream_handoff(self, path: str) -> None:
            """Decode tier: stream a handed-off request's tokens (SSE).

            The claim is exclusive — the queue is popped so two readers
            cannot interleave one stream."""
            try:
                rid = int(path.rsplit("/", 1)[1])
            except ValueError:
                return self._send(400, {"error": "handoff id must be int"})
            with handoff_lock:
                out = handoff_streams.pop(rid, None)
            if out is None:
                return self._send(404, {"error": f"no handoff {rid}"})
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.end_headers()
            try:
                while True:
                    tok = out.get()
                    if tok is None:
                        self.wfile.write(b"data: [DONE]\n\n")
                        return
                    if isinstance(tok, BaseException):
                        return  # truncate without [DONE]: SSE "broken"
                    ev = {"id": rid, "token": int(tok),
                          "text": engine.decode([tok])}
                    self.wfile.write(
                        b"data: " + json.dumps(ev).encode() + b"\n\n"
                    )
                    self.wfile.flush()
            except OSError:
                engine.serving.cancel(out)  # reader gone: free the slot

        def _load_adapter_route(self) -> None:
            """POST /v1/adapters {"name", "path", "alpha"?}: runtime
            adapter load/replace. 409 when pool slots are all pinned by
            in-flight requests (retryable); 400 on shape/rank mismatch."""
            length = int(self.headers.get("Content-Length", 0))
            try:
                req = json.loads(self.rfile.read(length) or b"{}")
            except json.JSONDecodeError as e:
                return self._send(400, {"error": f"bad json: {e}"})
            name, path = req.get("name"), req.get("path")
            if not name or not path:
                return self._send(
                    400, {"error": "`name` and `path` are required"}
                )
            try:
                slot = engine.load_adapter(
                    name, path, alpha=float(req.get("alpha", 16.0))
                )
            except (AdapterPoolFullError, AdapterBusyError) as e:
                return self._send(409, {"error": str(e)})
            except (ValueError, FileNotFoundError) as e:
                return self._send(400, {"error": str(e)})
            except RuntimeError as e:  # engine built without LoRA
                return self._send(400, {"error": str(e)})
            self._send(200, {"name": name, "slot": slot,
                             "model": f"{args.model_name}:{name}"})

        def do_DELETE(self):
            path = self.path.rstrip("/")
            prefix = "/v1/adapters/"
            if not path.startswith(prefix):
                return self._send(404, {"error": "not found"})
            name = path[len(prefix):]
            try:
                engine.serving.unload_adapter(name)
            except AdapterBusyError as e:
                return self._send(409, {"error": str(e)})
            except KeyError:
                return self._send(404, {"error": f"unknown adapter: {name}"})
            except RuntimeError as e:
                return self._send(400, {"error": str(e)})
            self._send(200, {"name": name, "unloaded": True})

        def do_POST(self):
            path = self.path.rstrip("/")
            if path == "/v1/adapters":
                return self._load_adapter_route()
            if path != "/v1/chat/completions":
                return self._send(404, {"error": "not found"})
            length = int(self.headers.get("Content-Length", 0))
            tenant = DEFAULT_TENANT
            try:
                req = json.loads(self.rfile.read(length) or b"{}")
                if req.get("stream"):
                    return self._stream(req)
                adapter, tenant = self._request_identity(req)
                tp, req_id = self._trace_identity()
                usage = {}
                text = engine.chat(req.get("messages", []),
                                   req.get("max_tokens"), req.get("temperature"),
                                   req.get("top_p"), req.get("stop"),
                                   usage_out=usage,
                                   adapter=adapter, tenant=tenant,
                                   traceparent=tp, x_request_id=req_id)
            except TenantShedError as e:
                return self._send_shed(e)
            except EngineOverloadedError as e:
                engine.record_tenant(tenant, shed=True)
                return self._send_overloaded(e)
            except KeyError as e:  # unknown adapter
                return self._send(404, {"error": f"unknown adapter: {e}"})
            except ValueError as e:  # bad request field (e.g. temperature)
                return self._send(400, {"error": str(e)})
            except Exception as e:  # surface engine errors as API errors
                return self._send(500, {"error": str(e)})
            finish = usage.pop("finish_reason", "length")
            handoff_id = usage.pop("handoff_id", None)
            self._send(200, {
                "id": "chatcmpl-native",
                "object": "chat.completion",
                "created": int(time.time()),
                "model": args.model_name,
                "choices": [{
                    "index": 0,
                    "message": {"role": "assistant", "content": text},
                    "finish_reason": finish,
                }],
                "usage": {**usage,
                          "total_tokens": sum(usage.values())} if usage else {},
                **({"handoff_id": handoff_id}
                   if handoff_id is not None else {}),
            })

    class ModelHTTPServer(ThreadingHTTPServer):
        # Accept backlog deeper than BaseServer's 5: bursts must reach
        # admission control and get a 429 + Retry-After, not a
        # kernel-level connection refusal indistinguishable from an
        # outage. Subclassed so the stdlib class is not mutated.
        request_queue_size = 64

    server = ModelHTTPServer(("0.0.0.0", args.port), Handler)
    print(f"native model server: {args.model_name} on :{args.port}", flush=True)
    if args.no_warmup:
        ready.set()
    else:
        # Warm in the background so /healthz (and early traffic, which
        # simply pays its own compiles) answer while programs build;
        # /readyz flips only after warmup_end.
        def _warm() -> None:
            try:
                r = engine.serving.warmup()
            except RuntimeError as e:
                # A request raced admission before warmup started (the
                # idle-check refused). Readiness still flips — the racer
                # is paying the compiles warmup would have.
                print(f"warmup skipped: {e}", flush=True)
            else:
                print(
                    f"warmup: {r['programs']} programs in"
                    f" {r['seconds']:.2f}s ({r['compiles']} built,"
                    f" {r['cache_hits']} from persistent cache)",
                    flush=True,
                )
            ready.set()

        threading.Thread(target=_warm, daemon=True, name="warmup").start()
    server.serve_forever()


if __name__ == "__main__":
    main()
