"""Framework-native model server: workloads.generate behind an
OpenAI-compatible HTTP API.

The JetStream/vLLM examples bring external engines; this one serves the
same llama-family checkpoints with dstack-tpu's own KV-cache decode loop
(workloads/generate.py) — the whole stack, orchestrator to tokens, is this
repo. Endpoints: GET /v1/models, POST /v1/chat/completions (non-stream).

The tokenizer here is a toy byte-level one so the example runs without
downloading a vocab (zero-egress test environments); swap in your
tokenizer for real checkpoints.
"""

import argparse
import itertools
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import jax
import jax.numpy as jnp

from dstack_tpu.workloads.config import PRESETS
from dstack_tpu.workloads.generate import generate
from dstack_tpu.workloads.transformer import init_params


class Engine:
    # Prompt lengths are bucketed so each bucket compiles ONCE — a fresh
    # XLA compile per novel prompt length would dominate request latency.
    MIN_BUCKET = 32

    def __init__(self, preset: str, max_new_tokens: int, checkpoint_dir: str = ""):
        self.config = PRESETS[preset]
        if max_new_tokens >= self.config.max_seq_len:
            raise SystemExit(
                f"--max-new-tokens {max_new_tokens} must be <"
                f" max_seq_len {self.config.max_seq_len} for {preset}"
            )
        self.max_new_tokens = max_new_tokens
        self._seed = itertools.count(
            int.from_bytes(__import__("os").urandom(4), "big")
        )
        self._seed_lock = threading.Lock()
        if checkpoint_dir:
            from dstack_tpu.workloads import checkpoint as ckpt
            from dstack_tpu.workloads.transformer import init_params as _init

            template = _init(self.config, jax.random.PRNGKey(0))
            # Prefer the params-only serving export (no optimizer moments
            # in memory); fall back to a full train-state restore.
            params = ckpt.restore_exported_params(checkpoint_dir, template)
            if params is None:
                from dstack_tpu.workloads.train import init_train_state

                state_tpl = init_train_state(self.config, jax.random.PRNGKey(0))
                restored = ckpt.restore_latest(checkpoint_dir, state_tpl)
                params = restored.params if restored is not None else template
            self.params = params
        else:
            self.params = init_params(self.config, jax.random.PRNGKey(0))
        self._generate = jax.jit(
            lambda p, t, key: generate(
                self.config, p, t, max_new_tokens=max_new_tokens,
                temperature=0.8, rng=key,
            )
        )

    def encode(self, text: str) -> jnp.ndarray:
        ids = [min(b, self.config.vocab_size - 1) for b in text.encode()] or [0]
        limit = self.config.max_seq_len - self.max_new_tokens
        ids = ids[-limit:] if limit > 0 else ids[:1]
        # Bucket to a power of two: pad short prompts left with newline
        # bytes, truncate the OLDEST bytes down to the bucket otherwise.
        bucket = self.MIN_BUCKET
        while bucket * 2 <= len(ids):
            bucket *= 2
        bucket = min(bucket, limit if limit > 0 else bucket)
        if len(ids) < bucket:
            ids = [10] * (bucket - len(ids)) + ids
        else:
            ids = ids[-bucket:]
        return jnp.asarray([ids], dtype=jnp.int32)

    def decode(self, ids) -> str:
        return bytes(int(t) % 256 for t in ids).decode("utf-8", errors="replace")

    def chat(self, messages) -> str:
        prompt = "\n".join(
            f"{m.get('role', 'user')}: {m.get('content', '')}" for m in messages
        )
        tokens = self.encode(prompt + "\nassistant:")
        with self._seed_lock:  # unique per request even within one ms
            seed = next(self._seed) % (2**31)
        out = self._generate(self.params, tokens, jax.random.PRNGKey(seed))
        return self.decode(out[0])


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--preset", default="smol-1b", choices=sorted(PRESETS))
    parser.add_argument("--port", type=int, default=9000)
    parser.add_argument("--model-name", default="dstack-tpu-native")
    parser.add_argument("--max-new-tokens", type=int, default=64)
    parser.add_argument("--checkpoint-dir", default="",
                        help="volume path with an Orbax checkpoint to serve")
    args = parser.parse_args()

    engine = Engine(args.preset, args.max_new_tokens, args.checkpoint_dir)

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _send(self, code: int, obj) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path.rstrip("/") == "/v1/models":
                return self._send(200, {
                    "object": "list",
                    "data": [{"id": args.model_name, "object": "model",
                              "created": 0, "owned_by": "dstack-tpu"}],
                })
            self._send(404, {"error": "not found"})

        def do_POST(self):
            if self.path.rstrip("/") != "/v1/chat/completions":
                return self._send(404, {"error": "not found"})
            length = int(self.headers.get("Content-Length", 0))
            try:
                req = json.loads(self.rfile.read(length) or b"{}")
                text = engine.chat(req.get("messages", []))
            except Exception as e:  # surface engine errors as API errors
                return self._send(500, {"error": str(e)})
            self._send(200, {
                "id": "chatcmpl-native",
                "object": "chat.completion",
                "created": int(time.time()),
                "model": args.model_name,
                "choices": [{
                    "index": 0,
                    "message": {"role": "assistant", "content": text},
                    "finish_reason": "length",
                }],
                "usage": {},
            })

    server = ThreadingHTTPServer(("0.0.0.0", args.port), Handler)
    print(f"native model server: {args.model_name} on :{args.port}", flush=True)
    server.serve_forever()


if __name__ == "__main__":
    main()
