"""North-star latency probe: submit -> first-step per FSM stage.

Measures the control-plane's share of the "`apply` -> first training step
< 5 min" target (BASELINE.md) on the local backend, where cloud boot and
image pull are out of the picture and ONLY orchestrator latency remains —
submit, run FSM, instance provision+handshake, runner submit, first output.

Two modes, same workload:
  event-driven  — the shipped design: background loops wake on ctx.kick()
                  the instant upstream state changes (background/__init__.py)
  polling       — the reference's design, simulated: kicks disabled, loops
                  tick at the reference's intervals (2s runs / 4s jobs,
                  APScheduler parity: reference background/__init__.py:47-76)

Emits ONE JSON document (LATENCY_r03.json via --out): per-stage timings for
both modes, single-host and a 4-host v5litepod-16 gang.

Run: python latency_probe.py [--out LATENCY_r03.json] [--runs 3]
"""

import argparse
import asyncio
import json
import statistics
import threading
import time


class ProbeServer:
    """In-process server on a real socket, optionally polling-mode."""

    def __init__(self, polling: bool, db_path: str = ":memory:",
                 backend_config: dict = None):
        self.polling = polling
        self.db_path = db_path
        self.backend_config = backend_config or {"tpu_sim": ["v5litepod-16"]}
        self.url = None
        self.token = None
        self._loop = None
        self._stop = None
        self._thread = None

    def start(self):
        from dstack_tpu.server import settings

        if self.polling:
            # Reference cadence (background/__init__.py:47-76 of the ref).
            settings.PROCESS_RUNS_INTERVAL = 2.0
            settings.PROCESS_JOBS_INTERVAL = 4.0
            settings.PROCESS_INSTANCES_INTERVAL = 4.0
        else:
            settings.PROCESS_RUNS_INTERVAL = 1.0
            settings.PROCESS_JOBS_INTERVAL = 1.0
            settings.PROCESS_INSTANCES_INTERVAL = 2.0
        started = threading.Event()

        def _run():
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)

            async def boot():
                from dstack_tpu.server.app import create_app
                from dstack_tpu.server.http import Server

                app = create_app(db_path=self.db_path)
                server = Server(app, "127.0.0.1", 0)
                await server.start()
                ctx = app.state["ctx"]
                # Default: advertise multi-host TPU slices (gang latency).
                ctx.overrides["local_backend_config"] = self.backend_config
                if self.polling:
                    ctx.kick = lambda channel: None  # reference has no kicks
                self.url = f"http://127.0.0.1:{server.port}"
                self.token = app.state["admin_token"]
                return server

            server = self._loop.run_until_complete(boot())
            self._stop = asyncio.Event()
            started.set()
            self._loop.run_until_complete(self._stop.wait())
            self._loop.run_until_complete(server.stop())
            self._loop.close()

        self._thread = threading.Thread(target=_run, daemon=True)
        self._thread.start()
        if not started.wait(20):
            raise RuntimeError("probe server did not start")
        return self

    def stop(self):
        if self._loop and self._stop:
            self._loop.call_soon_threadsafe(self._stop.set)
        self._thread.join(timeout=20)


def measure_run(client, config, run_name, timeout=180.0):
    """Submit and sample status at ~10ms; return per-stage offsets (s)."""
    from dstack_tpu.models.runs import RunStatus

    t0 = time.perf_counter()
    plan = client.runs.get_plan(config, run_name=run_name)
    t_plan = time.perf_counter() - t0
    run = client.runs.exec_plan(plan)
    t_submit = time.perf_counter() - t0

    stages = {}
    terminal = {RunStatus.DONE, RunStatus.FAILED, RunStatus.TERMINATED}
    deadline = t0 + timeout
    status = None
    while time.perf_counter() < deadline:
        run.refresh()
        status = run.status
        key = status.value
        if key not in stages:
            stages[key] = time.perf_counter() - t0
        if status in terminal:
            break
        time.sleep(0.01)
    if status not in terminal:
        raise TimeoutError(f"{run_name} stuck in {status}")

    # First log line arrival (the job echoes immediately -> proxy for
    # "first training step started").
    t_first_log = None
    log_deadline = time.perf_counter() + 30
    while time.perf_counter() < log_deadline:
        if any(True for _ in run.logs()):
            t_first_log = time.perf_counter() - t0
            break
        time.sleep(0.01)
    return {
        "plan_s": round(t_plan, 3),
        "submit_s": round(t_submit, 3),
        "stages_s": {k: round(v, 3) for k, v in stages.items()},
        "first_log_s": round(t_first_log, 3) if t_first_log else None,
        "final_status": status.value,
    }


def probe_mode(polling: bool, n_runs: int):
    from dstack_tpu.api import Client

    srv = ProbeServer(polling).start()
    try:
        client = Client(server_url=srv.url, token=srv.token, project_name="main")
        single = {"type": "task", "commands": ["echo first-step"],
                  "resources": {"cpu": "1..", "memory": "0.1.."}}
        gang = {"type": "task", "commands": ["echo gang-step rank=$JAX_PROCESS_ID"],
                "resources": {"tpu": "v5litepod-16", "memory": "0.1.."}}
        out = {"single_host": [], "gang_4host": []}
        for i in range(n_runs):
            out["single_host"].append(
                measure_run(client, single, f"lat-single-{i}"))
        for i in range(n_runs):
            out["gang_4host"].append(
                measure_run(client, gang, f"lat-gang-{i}"))
        client.api.close()
        return out
    finally:
        srv.stop()


def summarize(samples):
    firsts = [s["first_log_s"] for s in samples if s["first_log_s"]]
    runnings = [s["stages_s"].get("running") for s in samples]
    runnings = [r for r in runnings if r is not None]
    return {
        "submit_to_running_s": round(statistics.median(runnings), 3) if runnings else None,
        "submit_to_first_log_s": round(statistics.median(firsts), 3) if firsts else None,
        "samples": len(samples),
    }


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--out", default="LATENCY_r03.json")
    parser.add_argument("--runs", type=int, default=3)
    args = parser.parse_args()

    result = {"meta": {
        "workloads": {
            "single_host": "1-host cpu task",
            "gang_4host": "v5litepod-16 = 4-host gang, full JAX env injection",
        },
        "target": "apply->first step < 5 min (BASELINE.md); local backend "
                  "isolates orchestrator latency (no cloud boot/image pull)",
    }}
    for mode, polling in (("event_driven", False), ("polling_reference", True)):
        runs = probe_mode(polling, args.runs)
        result[mode] = {
            "single_host": {"summary": summarize(runs["single_host"]),
                            "runs": runs["single_host"]},
            "gang_4host": {"summary": summarize(runs["gang_4host"]),
                           "runs": runs["gang_4host"]},
        }
    ev = result["event_driven"]["gang_4host"]["summary"]["submit_to_first_log_s"]
    poll = result["polling_reference"]["gang_4host"]["summary"]["submit_to_first_log_s"]
    result["speedup_gang_first_log"] = round(poll / ev, 2) if ev and poll else None
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps({
        "event_driven_gang_first_log_s": ev,
        "polling_gang_first_log_s": poll,
        "speedup": result["speedup_gang_first_log"],
    }))


if __name__ == "__main__":
    main()
